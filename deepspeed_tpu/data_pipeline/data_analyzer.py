"""Offline dataset difficulty analyzer.

Reference analog: ``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer`` — maps metric functions over the dataset in worker shards,
writes per-sample metric files, then merges). The reference persists into its
custom mmap indexed-dataset format; we persist plain ``.npy`` arrays per metric
(hosts have plenty of RAM for index arrays; the token data itself stays in
``indexed_dataset.py`` files).

Output layout per metric under ``save_path``::

    <metric>/sample_values.npy        float64[num_samples] difficulty per sample
    <metric>/index_to_sample.npy      int64[num_samples] argsort by value
    <metric>/worker_<i>_<n>.npy       partial shards before merge
"""

import os
from typing import Callable, Dict, Sequence

import numpy as np


class DataAnalyzer:

    def __init__(self,
                 dataset: Sequence,
                 metric_functions: Dict[str, Callable],
                 save_path: str,
                 worker_id: int = 0,
                 num_workers: int = 1,
                 batch_size: int = 1024):
        self.dataset = dataset
        self.metric_functions = dict(metric_functions)
        self.save_path = save_path
        self.worker_id = worker_id
        self.num_workers = num_workers
        self.batch_size = batch_size

    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def run_map(self) -> None:
        """Compute this worker's shard of every metric and persist it."""
        lo, hi = self._worker_range()
        results = {name: [] for name in self.metric_functions}
        for start in range(lo, hi, self.batch_size):
            chunk = [self.dataset[i] for i in range(start, min(hi, start + self.batch_size))]
            for name, fn in self.metric_functions.items():
                vals = np.asarray([fn(sample) for sample in chunk], dtype=np.float64)
                results[name].append(vals)
        for name, parts in results.items():
            mdir = os.path.join(self.save_path, name)
            os.makedirs(mdir, exist_ok=True)
            shard = np.concatenate(parts) if parts else np.empty(0, dtype=np.float64)
            np.save(os.path.join(
                mdir, f"worker_{self.worker_id}_{self.num_workers}.npy"), shard)

    def run_reduce(self) -> None:
        """Merge all worker shards into sample_values + index_to_sample."""
        for name in self.metric_functions:
            mdir = os.path.join(self.save_path, name)
            parts = []
            for w in range(self.num_workers):
                path = os.path.join(mdir, f"worker_{w}_{self.num_workers}.npy")
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"metric '{name}': missing shard from worker {w} ({path})")
                parts.append(np.load(path))
            values = np.concatenate(parts)
            np.save(os.path.join(mdir, "sample_values.npy"), values)
            np.save(os.path.join(mdir, "index_to_sample.npy"),
                    np.argsort(values, kind="stable").astype(np.int64))

    @staticmethod
    def load_metric(save_path: str, metric_name: str) -> np.ndarray:
        return np.load(os.path.join(save_path, metric_name, "sample_values.npy"))
