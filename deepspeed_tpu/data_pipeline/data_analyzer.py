"""Offline dataset difficulty analyzer.

Reference analog: ``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer`` — maps metric functions over the dataset in worker shards,
writes per-sample metric files, then merges; 880 LoC with mmap-backed metric
files and a distributed multi-node map/reduce). Mirrored here:

- **map**: each worker computes its contiguous shard of every metric and
  persists it as an ``.npy`` shard file (written via ``open_memmap`` so a
  shard larger than RAM streams to disk in ``batch_size`` chunks).
- **reduce**: shards stream into one mmap-backed ``sample_values.npy`` —
  the merged values never materialize in RAM (reference: the mmap
  indexed-dataset merge); only the int64 sort index is in-memory (same
  lower bound as the reference's ``index_to_sample`` build).
- **metric types** (reference ``metric_type`` knob):
  ``single_value_per_sample`` (difficulty per sample, default) and
  ``accumulate_value_over_samples`` (one running vector summed across the
  dataset, e.g. vocabulary counts — workers write partials, reduce sums).
- **metric→sample map** (reference ``metric_to_sample_dict``): for discrete
  metrics, a CSR-style index (``unique_values / offsets / sample_ids``) so
  curriculum binning can look up all samples at a difficulty level without
  scanning.
- **distributed**: ``run_map_reduce`` runs map on every jax process and
  reduce on process 0, with a cross-host barrier between (the reference
  drives this with torch.distributed barriers; here any barrier callable —
  default ``jax.experimental.multihost_utils.sync_global_devices`` when
  jax.distributed is live).

Output layout per metric under ``save_path``::

    <metric>/sample_values.npy        float64[num_samples] difficulty/sample
    <metric>/index_to_sample.npy      int64[num_samples] argsort by value
    <metric>/unique_values.npy        CSR map (discrete metrics)
    <metric>/offsets.npy              int64[n_unique + 1]
    <metric>/sample_ids.npy           int64[num_samples] grouped by value
    <metric>/worker_<i>_<n>.npy       partial shards before merge
"""

import os
from typing import Callable, Dict, Optional, Sequence

import numpy as np

SINGLE_VALUE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"


class DataAnalyzer:

    def __init__(self,
                 dataset: Sequence,
                 metric_functions: Dict[str, Callable],
                 save_path: str,
                 worker_id: int = 0,
                 num_workers: int = 1,
                 batch_size: int = 1024,
                 metric_types: Optional[Dict[str, str]] = None,
                 build_value_map: bool = True):
        self.dataset = dataset
        self.metric_functions = dict(metric_functions)
        self.save_path = save_path
        self.worker_id = worker_id
        self.num_workers = num_workers
        self.batch_size = batch_size
        self.metric_types = dict(metric_types or {})
        for name, t in self.metric_types.items():
            if t not in (SINGLE_VALUE, ACCUMULATE):
                raise ValueError(f"metric '{name}': unknown metric_type {t!r}")
        self.build_value_map = build_value_map

    def _type(self, name: str) -> str:
        return self.metric_types.get(name, SINGLE_VALUE)

    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = min(n, self.worker_id * per)   # trailing workers may be empty
        return lo, min(n, lo + per)

    def _shard_path(self, name: str, worker: int) -> str:
        return os.path.join(self.save_path, name,
                            f"worker_{worker}_{self.num_workers}.npy")

    def run_map(self) -> None:
        """Compute this worker's shard of every metric and persist it.

        single-value shards are written through an ``open_memmap`` in
        ``batch_size`` chunks, so a shard bigger than RAM never lives in
        memory; accumulate metrics keep one running vector."""
        lo, hi = self._worker_range()
        for name in self.metric_functions:
            os.makedirs(os.path.join(self.save_path, name), exist_ok=True)
        single = [n for n in self.metric_functions
                  if self._type(n) == SINGLE_VALUE]
        accum = {n: None for n in self.metric_functions
                 if self._type(n) == ACCUMULATE}
        shards = {name: np.lib.format.open_memmap(
            self._shard_path(name, self.worker_id), mode="w+",
            dtype=np.float64, shape=(hi - lo,)) for name in single}
        for start in range(lo, hi, self.batch_size):
            end = min(hi, start + self.batch_size)
            chunk = [self.dataset[i] for i in range(start, end)]
            for name in single:
                fn = self.metric_functions[name]
                shards[name][start - lo:end - lo] = np.asarray(
                    [fn(s) for s in chunk], dtype=np.float64)
            for name in accum:
                fn = self.metric_functions[name]
                for s in chunk:
                    v = np.asarray(fn(s), dtype=np.float64)
                    accum[name] = v if accum[name] is None else accum[name] + v
        for name, mm in shards.items():
            mm.flush()
            del mm
        for name, total in accum.items():
            if total is None:
                total = np.zeros(0, np.float64)
            np.save(self._shard_path(name, self.worker_id), total)

    def run_reduce(self) -> None:
        """Merge all worker shards.

        single-value: stream shards into one mmap-backed ``sample_values.npy``
        (no in-RAM concatenation), then build ``index_to_sample`` (int64 sort
        index — the only O(n) RAM) and, for discrete metrics, the CSR
        metric→sample map. accumulate: sum the partial vectors."""
        n = len(self.dataset)
        for name in self.metric_functions:
            mdir = os.path.join(self.save_path, name)
            paths = [self._shard_path(name, w) for w in range(self.num_workers)]
            for w, path in enumerate(paths):
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"metric '{name}': missing shard from worker {w} ({path})")
            if self._type(name) == ACCUMULATE:
                total = None
                for path in paths:
                    part = np.load(path)
                    if part.size == 0:   # empty-range worker's placeholder
                        continue
                    total = part if total is None else total + part
                np.save(os.path.join(mdir, "sample_values.npy"),
                        total if total is not None else np.zeros(0))
                continue
            out = np.lib.format.open_memmap(
                os.path.join(mdir, "sample_values.npy"), mode="w+",
                dtype=np.float64, shape=(n,))
            pos = 0
            for path in paths:
                shard = np.load(path, mmap_mode="r")
                for start in range(0, shard.shape[0], self.batch_size):
                    end = min(shard.shape[0], start + self.batch_size)
                    out[pos + start:pos + end] = shard[start:end]
                pos += shard.shape[0]
            assert pos == n, (pos, n)
            out.flush()
            order = np.argsort(out, kind="stable").astype(np.int64)
            np.save(os.path.join(mdir, "index_to_sample.npy"), order)
            if self.build_value_map:
                # CSR metric->sample map (reference metric_to_sample_dict):
                # out[order] is sorted, so its run-lengths give the bucket
                # boundaries and `order` itself is sample_ids grouped by
                # value. Run-lengths stream in batch_size chunks so the
                # sorted values never materialize in RAM either
                uniq, counts = [], []
                cur, cnt = None, 0
                for start in range(0, n, self.batch_size):
                    chunk = np.asarray(out)[order[start:start +
                                                  self.batch_size]]
                    for v, c in zip(*np.unique(chunk, return_counts=True)):
                        if cur is not None and v == cur:
                            cnt += int(c)
                        else:
                            if cur is not None:
                                uniq.append(cur)
                                counts.append(cnt)
                            cur, cnt = v, int(c)
                if cur is not None:
                    uniq.append(cur)
                    counts.append(cnt)
                offsets = np.zeros(len(uniq) + 1, np.int64)
                np.cumsum(np.asarray(counts, np.int64), out=offsets[1:])
                np.save(os.path.join(mdir, "unique_values.npy"),
                        np.asarray(uniq, np.float64))
                np.save(os.path.join(mdir, "offsets.npy"), offsets)
                np.save(os.path.join(mdir, "sample_ids.npy"), order)
            del out

    # ------------------------------------------------------------------
    def run_map_reduce(self, barrier: Optional[Callable] = None) -> None:
        """Distributed map/reduce over jax processes (reference:
        run_map_reduce with torch.distributed barriers): every process maps
        its shard (worker_id = process_index), a cross-host barrier commits
        the shard files, process 0 reduces, and a final barrier releases the
        readers."""
        import jax
        nproc = jax.process_count()
        if nproc > 1:
            self.worker_id = jax.process_index()
            self.num_workers = nproc
        if barrier is None and nproc > 1:
            from jax.experimental import multihost_utils

            def barrier(tag):
                multihost_utils.sync_global_devices(tag)
        self.run_map()
        if barrier is not None:
            barrier("dstpu_data_analyzer_map")
        if self.worker_id == 0:
            self.run_reduce()
        if barrier is not None:
            barrier("dstpu_data_analyzer_reduce")

    @staticmethod
    def load_metric(save_path: str, metric_name: str,
                    mmap: bool = False) -> np.ndarray:
        return np.load(os.path.join(save_path, metric_name,
                                    "sample_values.npy"),
                       mmap_mode="r" if mmap else None)

    @staticmethod
    def samples_with_value(save_path: str, metric_name: str,
                           value: float) -> np.ndarray:
        """All sample ids whose metric equals ``value`` (CSR lookup —
        reference metric_to_sample_dict access for curriculum binning)."""
        mdir = os.path.join(save_path, metric_name)
        uniq = np.load(os.path.join(mdir, "unique_values.npy"))
        i = np.searchsorted(uniq, value)
        if i >= len(uniq) or uniq[i] != value:
            return np.empty(0, np.int64)
        offsets = np.load(os.path.join(mdir, "offsets.npy"))
        ids = np.load(os.path.join(mdir, "sample_ids.npy"), mmap_mode="r")
        return np.asarray(ids[offsets[i]:offsets[i + 1]])
