"""Sequence packing — variable-length documents into fixed [B, S] batches.

Reference analog: none in-tree (the reference trains on pre-packed Megatron
data); packing is the standard TPU-side answer to static shapes: XLA wants
one [B, S] geometry, so short documents concatenate into rows with
``segment_ids`` confining attention (masked IN-KERNEL by the flash kernel,
under Ulysses, and under ring CP — see ops/pallas/flash_attention.py),
``positions`` restarting per document (RoPE must not see cross-document
offsets), and ``loss_mask`` zeroing the cross-document boundary token (the
last token of doc i must not predict the first token of doc i+1).

Greedy first-fit packing: documents are placed into the first open row with
room (documents longer than ``seq_len`` are split). Rows pad with
``pad_token`` under segment id -1 (mismatches every real segment) and zero
loss mask.
"""

from typing import Dict, Iterable, List, Sequence

import numpy as np


def pack_sequences(docs: Iterable[Sequence[int]], batch_size: int,
                   seq_len: int, pad_token: int = 0) -> List[Dict[str, np.ndarray]]:
    """Pack token documents into batches of ``{input_ids, segment_ids,
    positions, loss_mask}`` arrays [B, S]. Returns every FULL batch plus a
    final partial batch (padded rows) if any tokens remain."""
    rows: List[List[np.ndarray]] = []          # per open row: list of docs
    lens: List[int] = []

    def split(doc):
        doc = np.asarray(doc, np.int32)
        for a in range(0, len(doc), seq_len):
            yield doc[a:a + seq_len]

    for doc in docs:
        for piece in split(doc):
            for i, used in enumerate(lens):
                if used + len(piece) <= seq_len:
                    rows[i].append(piece)
                    lens[i] += len(piece)
                    break
            else:
                rows.append([piece])
                lens.append(len(piece))

    batches = []
    for a in range(0, len(rows), batch_size):
        chunk = rows[a:a + batch_size]
        if len(chunk) < batch_size:
            chunk = chunk + [[] for _ in range(batch_size - len(chunk))]
        ids = np.full((batch_size, seq_len), pad_token, np.int32)
        seg = np.full((batch_size, seq_len), -1, np.int32)
        pos = np.zeros((batch_size, seq_len), np.int32)
        mask = np.zeros((batch_size, seq_len), np.float32)
        for r, pieces in enumerate(chunk):
            off = 0
            for s, piece in enumerate(pieces):
                n = len(piece)
                ids[r, off:off + n] = piece
                seg[r, off:off + n] = s
                pos[r, off:off + n] = np.arange(n)
                # loss_mask[p] = 1 iff token p is a trainable TARGET — the
                # convention of the model's shifted loss (prediction from
                # position t is gated by loss_mask[t+1]): a document's first
                # token has no in-document predictor, padding has none at all
                mask[r, off + 1:off + n] = 1.0
                off += n
        batches.append({"input_ids": ids, "segment_ids": seg,
                        "positions": pos, "loss_mask": mask})
    return batches


def packing_efficiency(batches: List[Dict[str, np.ndarray]]) -> float:
    """Fraction of token slots holding real (non-padding) tokens."""
    total = real = 0
    for b in batches:
        total += b["segment_ids"].size
        real += int((b["segment_ids"] >= 0).sum())
    return real / max(total, 1)
