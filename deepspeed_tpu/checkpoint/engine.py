"""Checkpoint save/load — single logical sharded checkpoint, reshape-on-load.

Reference analogs:
- ``runtime/engine.py:3109 save_checkpoint`` / ``:2763 load_checkpoint`` (per-rank
  ``mp_rank_XX_model_states.pt`` + per-dp-rank optim shards, ``latest`` tag file)
- ``runtime/checkpoint_engine/checkpoint_engine.py`` (pluggable engine ABC)
- ``deepspeed/checkpoint/ds_to_universal.py`` universal checkpoint (per-parameter
  atomic files enabling TP/PP/DP reshape on resume)

TPU-native design (SURVEY.md §5.4): orbax/tensorstore OCDBT writes ONE logical
checkpoint where every array is stored parameter-atomically regardless of its runtime
sharding — so *every* checkpoint is a "universal checkpoint": loading onto a different
mesh/world size just reads each array with the new sharding. The offline
``ds_to_universal`` converter is unnecessary by construction.

The ``latest`` tag-file protocol is kept for API parity.
"""

import json
import os
import zlib
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"
MANIFEST_FILE = "ds_manifest.json"

#: ds_meta.json provenance block schema version
PROVENANCE_VERSION = 1

#: The data-sampler determinism contract recorded in every checkpoint and
#: honored on resume at ANY world size: the stream position is
#: ``consumed_samples`` (== engine.global_samples), so the resumed run's
#: next global batch must start at that sample index — no sample dropped,
#: none double-trained. ``epoch = consumed_samples // dataset_size`` for
#: sized datasets. ``train_batch_size`` must be unchanged across resume
#: (the elastic invariant): it keeps ``step k <-> samples k*batch``
#: bijective, so step-keyed deterministic data (batch_fn(step)) and
#: sample-keyed loaders resume to the same position regardless of how the
#: batch is re-factored into (micro_batch, gas, dp_world) at the new mesh.
SAMPLER_CONTRACT = ("next_sample_index == consumed_samples; "
                    "epoch == consumed_samples // dataset_size; "
                    "train_batch_size invariant across resume")


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed its integrity-manifest verification: a listed
    file is missing or its checksum no longer matches — the checkpoint is
    torn and must never be restored."""


class CheckpointProvenanceError(RuntimeError):
    """The checkpoint's recorded provenance (``ds_meta.json``) is
    incompatible with the engine trying to restore it: a different model
    (parameter tree mismatch) or a broken sampler contract (changed
    ``train_batch_size``). A *mesh/world/zero-tier* change is NOT an error
    — that is the mesh-portable-resume capability; this error exists so
    the genuinely-incompatible cases are classified up front instead of
    surfacing as an orbax shape crash mid-restore."""


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


# ---------------------------------------------------------------------------
# durability primitives: fsync + integrity manifest + atomic commit
# ---------------------------------------------------------------------------
def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Flush directory entries (the rename/create records) to disk; no-op on
    platforms whose directory fds reject fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _file_crc(path: str, chunk: int = 1 << 20):
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            size += len(buf)
    return size, crc


def write_manifest(path: str, extra_meta: Optional[Dict[str, Any]] = None,
                   exclude=None) -> Dict[str, Any]:
    """Walk the checkpoint dir, checksum every file (crc32 + size), persist
    ``ds_manifest.json`` and fsync it + every hashed file. Written strictly
    BEFORE the ``latest`` commit: a committed tag therefore always carries a
    verifiable manifest, and a crash mid-save leaves a tag that simply never
    commits. ``exclude(filename) -> bool`` skips files another process may
    still be writing (no cross-process barrier exists here — checksumming a
    peer's in-flight sidecar would brand a good checkpoint torn forever)."""
    files: Dict[str, Dict[str, int]] = {}
    for root, _, names in os.walk(path):
        for name in sorted(names):
            if name == MANIFEST_FILE:
                continue
            if exclude is not None and exclude(name):
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            size, crc = _file_crc(full)
            files[rel] = {"size": size, "crc32": crc}
            _fsync_file(full)
    manifest = {"version": 1, "files": files, "meta": extra_meta or {}}
    mpath = os.path.join(path, MANIFEST_FILE)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(path)
    return manifest


def verify_manifest(path: str) -> bool:
    """Re-checksum a checkpoint against its manifest. Returns True when the
    manifest exists and every listed file matches; False for a legacy
    (manifest-less) checkpoint; raises ``CheckpointCorruptionError`` on any
    missing file or checksum mismatch."""
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(mpath):
        return False
    with open(mpath) as f:
        manifest = json.load(f)
    for rel, want in manifest.get("files", {}).items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            raise CheckpointCorruptionError(
                f"checkpoint {path}: manifest file missing: {rel}")
        size, crc = _file_crc(full)
        if size != want["size"] or crc != want["crc32"]:
            raise CheckpointCorruptionError(
                f"checkpoint {path}: checksum mismatch for {rel} "
                f"(size {size} vs {want['size']}, crc {crc} vs {want['crc32']})")
    return True


def is_committed(save_dir: str, tag: str, verify: bool = True) -> bool:
    """True when ``tag`` is a fully-committed, integrity-clean checkpoint
    (manifest verification failures count as not-committed rather than
    raising — callers use this to pick a fallback tag)."""
    path = _ckpt_dir(save_dir, tag)
    if not os.path.isdir(path) or not os.path.exists(
            os.path.join(path, "ds_meta.json")):
        return False
    if not verify:
        return True
    try:
        verify_manifest(path)
    except CheckpointCorruptionError as e:
        logger.warning(f"checkpoint integrity: {e}")
        return False
    return True


def read_latest_tag(save_dir: str) -> Optional[str]:
    """The tag the ``latest`` pointer names, or None — the single reader for
    the pointer protocol (resume discovery, pruning, env_report, and the
    load path all go through here)."""
    latest = os.path.join(os.path.abspath(save_dir), LATEST_FILE)
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        return f.read().strip() or None


def _commit_latest(save_dir: str, tag: str) -> None:
    """Atomically publish ``tag`` as the latest committed checkpoint:
    tmp-file + fsync + rename + directory fsync, so a host crash at any
    instant leaves either the old pointer or the new one — never a torn
    ``latest``."""
    save_dir = os.path.abspath(save_dir)
    tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
    with open(tmp, "w") as f:
        f.write(tag)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(save_dir, LATEST_FILE))
    _fsync_dir(save_dir)


def wait_pending_checkpoint(engine) -> None:
    """Block until a previous async save (if any) has fully committed, and
    re-raise any error the background finalizer hit (reference: nebula async
    checkpoint engine's commit barrier)."""
    t = getattr(engine, "_pending_ckpt", None)
    if t is not None:
        t.join()
        engine._pending_ckpt = None
        err = getattr(engine, "_pending_ckpt_error", None)
        if err is not None:
            engine._pending_ckpt_error = None
            raise RuntimeError("async checkpoint save failed") from err


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[Dict[str, Any]] = None,
                           async_save: Optional[bool] = None) -> str:
    """``async_save`` (default: engine config ``checkpoint.async_save``):
    orbax fetches the arrays synchronously (so the training step may donate
    buffers immediately after return) and persists + commits the ``latest``
    tag from a background thread — the reference's Nebula-style async engine
    (``runtime/checkpoint_engine/nebula_checkpoint_engine.py``)."""
    if async_save is None:
        async_save = bool(getattr(engine.config, "checkpoint_config",
                                  None) and
                          engine.config.checkpoint_config.async_save)
    wait_pending_checkpoint(engine)          # one in flight at a time
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    path = _ckpt_dir(save_dir, tag)
    state = engine.state
    offload = getattr(engine, "_offload", None)
    params_to_save = state.params
    if offload is not None:
        # Under offload the authoritative weights are the fp32 host masters
        # (device params are compute-dtype shadows) — save those so the
        # checkpoint stays fp32 regardless of offload config.
        params_to_save = jax.tree_util.tree_unflatten(
            engine._params_treedef, offload.masters())
    composite = {
        "params": params_to_save,
        "opt_state": state.opt_state,
        "scalars": {
            "step": state.step,
            "loss_scale": state.loss_scale.scale,
            "good_steps": state.loss_scale.good_steps,
            "hysteresis": state.loss_scale.hysteresis,
            "skipped_steps": state.skipped_steps,
        },
    }
    ckptr = ocp.StandardCheckpointer()
    # orbax's save is async by design: device->host fetch happens before it
    # returns, disk persistence + atomic rename happen in the background
    ckptr.save(path, composite, force=True)

    # sidecar state (host optimizer moments, compression masks, step counters)
    # mutates every train_batch — snapshot it NOW so async persistence commits
    # a consistent point-in-time checkpoint
    sidecars = _snapshot_sidecars(engine, client_state)

    def _finalize():
        try:
            ckptr.wait_until_finished()
            ckptr.close()
            _write_sidecars_and_commit(save_dir, tag, path, sidecars)
        except BaseException as e:
            if async_save:                   # surfaced by wait_pending_checkpoint
                engine._pending_ckpt_error = e
            raise

    if async_save:
        import threading
        # non-daemon: a save in flight at interpreter exit completes instead
        # of silently losing the run's final checkpoint
        t = threading.Thread(target=_finalize, daemon=False,
                             name="dstpu-async-ckpt")
        t.start()
        engine._pending_ckpt = t
        log_dist(f"async checkpoint scheduled: {path}", ranks=[0])
        return path
    _finalize()
    return path


def _param_fingerprint(engine) -> Dict[str, Any]:
    """Name/shape inventory of the parameter tree (dtype-free: offload
    checkpoints are fp32 masters while live params may be compute-dtype).
    The sha256 over the ordered ``name:shape`` lines is the compatibility
    key a resume checks BEFORE touching orbax."""
    import hashlib
    if getattr(engine, "_param_offload", None) is not None:
        tree = engine._param_offload.masters_tree()
    else:
        tree = engine.state.params
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    lines = [f"{jax.tree_util.keystr(path)}:{tuple(np.shape(leaf))}"
             for path, leaf in flat]
    return {
        "count": int(sum(int(np.prod(np.shape(leaf) or (1,)))
                         for _, leaf in flat)),
        "leaves": len(lines),
        "tree": lines,
        "tree_sha256": hashlib.sha256("\n".join(lines).encode()).hexdigest(),
    }


def _rng_record(engine) -> Dict[str, Any]:
    """The engine's live PRNG key, host-serialized — restored on resume so
    the per-step rng stream (dropout etc.) continues exactly where the
    save left it, at any world size (the key is replicated host state)."""
    key = engine._rng
    try:
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            data = np.asarray(jax.random.key_data(key))
            impl = jax.random.key_impl(key)
            return {"impl": getattr(impl, "name", None) or str(impl),
                    "typed": True,
                    "dtype": str(data.dtype), "shape": list(data.shape),
                    "data": data.tolist()}
    except (TypeError, AttributeError):
        pass
    arr = np.asarray(jax.device_get(key))
    return {"typed": False, "dtype": str(arr.dtype),
            "shape": list(arr.shape), "data": arr.tolist()}


def _restore_rng(engine, rec: Dict[str, Any]) -> None:
    data = np.asarray(rec["data"], dtype=rec.get("dtype", "uint32"))
    if rec.get("typed"):
        # the impl rides in provenance for a reason: wrapping rbg-shaped
        # key data under a process whose default impl is threefry (or vice
        # versa) would mis-wrap or raise — the saved impl wins
        impl = rec.get("impl")
        engine._rng = jax.random.wrap_key_data(
            data, impl=impl) if impl else jax.random.wrap_key_data(data)
    else:
        engine._rng = jnp.asarray(data)


def _ledger_provenance(engine) -> Dict[str, Any]:
    """Analytic per-device memory plan + the observed HBM limit at save
    time — what a shrink-aware relauncher preflights a smaller world
    against without touching devices (the saved config rides alongside in
    ``provenance.config``, so ``MemoryLedger.from_config`` can re-plan any
    candidate world offline)."""
    out: Dict[str, Any] = {}
    try:
        ledger = engine.memory_ledger()
        phases = ledger.phase_bytes()
        out["phase_hbm_bytes"] = {
            ph: int(v.get("hbm_bytes", 0)) for ph, v in phases.items()}
        out["max_hbm_bytes"] = int(ledger.max_hbm_bytes())
        out["zero_world"] = int(ledger.zero_world)
    except Exception:
        logger.exception("provenance: memory ledger unavailable")
    limit = 0
    try:
        for s in engine.accelerator.memory_stats().values():
            limit = max(limit, int(s.get("bytes_limit", 0)))
    except Exception:
        pass
    out["bytes_limit"] = limit
    return out


def checkpoint_provenance(engine) -> Dict[str, Any]:
    """The ``ds_meta.json`` provenance block: everything a resume at a
    DIFFERENT world/mesh/zero-tier needs to classify compatibility and
    re-plan placement before any array byte is read."""
    from deepspeed_tpu.runtime.zero.partition import zero_placement
    mesh_shape = {str(k): int(v) for k, v in engine.mesh.shape.items()}
    zc = engine.config.zero_config
    return {
        "version": PROVENANCE_VERSION,
        "world": {
            "process_count": int(jax.process_count()),
            "device_count": int(np.prod(list(mesh_shape.values()))),
        },
        "mesh": mesh_shape,
        "zero": zero_placement(mesh_shape, engine.zero_stage,
                               offload_optimizer=zc.offload_optimizer.device,
                               offload_param=zc.offload_param.device),
        "batch": {
            "train_batch_size": int(engine.train_batch_size),
            "micro_batch": int(engine.micro_batch_size),
            "gradient_accumulation_steps":
                int(engine.gradient_accumulation_steps),
            "dp_world": int(engine.dp_world_size),
        },
        "sampler": {
            "consumed_samples": int(engine.global_samples),
            "contract": SAMPLER_CONTRACT,
        },
        "rng": _rng_record(engine),
        "params": _param_fingerprint(engine),
        "ledger": _ledger_provenance(engine),
        "config": engine.config.raw(),
    }


def check_provenance(engine, meta: Dict[str, Any], path: str,
                     strict: bool = True) -> Optional[Dict[str, Any]]:
    """Classify checkpoint-vs-engine compatibility from ``ds_meta.json``
    BEFORE the orbax restore. Returns the provenance block (None for
    legacy checkpoints). Raises ``CheckpointProvenanceError`` on a model
    mismatch or a broken sampler/batch contract; a mesh/world/zero change
    only logs + stamps an ``elastic/reshard`` instant."""
    prov = meta.get("provenance")
    if not prov:
        return None

    saved_fp = prov.get("params") or {}
    if saved_fp.get("tree_sha256"):
        cur = _param_fingerprint(engine)
        if cur["tree_sha256"] != saved_fp["tree_sha256"]:
            saved_tree = saved_fp.get("tree") or []
            diff = [f"  saved: {s!r}  !=  engine: {c!r}"
                    for s, c in zip(saved_tree, cur["tree"]) if s != c]
            if len(saved_tree) != len(cur["tree"]):
                diff.append(f"  leaf count: saved {len(saved_tree)} != "
                            f"engine {len(cur['tree'])}")
            raise CheckpointProvenanceError(
                f"checkpoint {path} was saved from a different model: "
                f"parameter tree mismatch ({saved_fp.get('count')} vs "
                f"{cur['count']} params). First differences:\n"
                + "\n".join(diff[:5] or ["  (tree hash differs)"]))

    saved_tb = (prov.get("batch") or {}).get("train_batch_size")
    if saved_tb and int(saved_tb) != int(engine.train_batch_size):
        msg = (f"checkpoint {path} breaks the sampler contract: saved "
               f"train_batch_size {saved_tb} != engine "
               f"{engine.train_batch_size}. The global batch is the elastic "
               f"invariant — resume must re-factor (micro_batch, gas, "
               f"dp_world) at the new mesh, not change the global batch "
               f"(else 'step k <-> samples k*batch' breaks and samples are "
               f"dropped/double-trained). Pass strict_provenance=False to "
               f"override deliberately.")
        if strict:
            raise CheckpointProvenanceError(msg)
        logger.warning(msg + " (override active: consumed_samples stays "
                       "authoritative for the data position)")

    saved_mesh = prov.get("mesh") or {}
    cur_mesh = {str(k): int(v) for k, v in engine.mesh.shape.items()}
    saved_zero = prov.get("zero") or {}
    if saved_mesh and saved_mesh != cur_mesh:
        saved_world = (prov.get("world") or {}).get("device_count", "?")
        cur_world = int(np.prod(list(cur_mesh.values())))
        log_dist(
            f"mesh-portable resume: checkpoint saved at world {saved_world} "
            f"mesh {saved_mesh} (zero stage {saved_zero.get('stage', '?')}), "
            f"restoring onto world {cur_world} mesh {cur_mesh} (zero stage "
            f"{engine.zero_stage}) — re-sharding from the parameter-atomic "
            f"store", ranks=[0])
        engine.tracer.instant(
            "elastic/reshard", cat="elastic",
            saved_world=saved_world, new_world=cur_world,
            saved_zero_stage=saved_zero.get("stage"),
            new_zero_stage=engine.zero_stage,
            consumed_samples=(prov.get("sampler")
                              or {}).get("consumed_samples"))
    return prov


def _extract_named_subtrees(tree, name: str, out: list) -> None:
    """Depth-first collect every subtree stored under dict key ``name``
    (orbax renders optax NamedTuples as dicts keyed by field name)."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            if k == name:
                out.append(tree[k])
            else:
                _extract_named_subtrees(tree[k], name, out)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            _extract_named_subtrees(v, name, out)


def _extract_moments(opt_tree, shapes, n_states: int):
    """Mine per-parameter optimizer moments out of a host-restored optax
    state tree: the ``mu``/``nu`` (adam) or ``trace`` (momentum) subtrees
    whose flattened leaves match the parameter shapes in order. Returns
    ``(states, step_count)`` for ``HostOffloadOptimizer.load_state_dict``,
    or ``(None, 0)`` when the structure is unrecognized (caller resets
    moments with a warning — never a crash)."""
    names = ("mu", "nu") if n_states == 2 else ("trace", "mu")
    per_state = []
    for nm in names:
        found: list = []
        _extract_named_subtrees(opt_tree, nm, found)
        match = None
        for cand in found:
            leaves = [np.asarray(jax.device_get(l))
                      for l in jax.tree_util.tree_leaves(cand)]
            if len(leaves) == len(shapes) and all(
                    l.shape == tuple(s) for l, s in zip(leaves, shapes)):
                match = leaves
                break
        if match is None:
            continue
        per_state.append(match)
        if len(per_state) == n_states:
            break
    if len(per_state) != n_states:
        return None, 0
    counts: list = []
    _extract_named_subtrees(opt_tree, "count", counts)
    step_count = 0
    for c in counts:
        try:
            step_count = max(step_count,
                             int(np.asarray(jax.device_get(c))))
        except (TypeError, ValueError):
            pass
    return [[per_state[s][i] for s in range(n_states)]
            for i in range(len(shapes))], step_count


def _inject_moments_into_optax(opt_state, params_treedef, states,
                               step_count: int):
    """The reverse adaptation (offload-tier checkpoint -> optax engine,
    the ladder DE-escalation when capacity regrows): graft host moment
    arrays into a live optax state's ``mu``/``nu``/``trace`` fields and
    stamp ``count``. Returns the new state, or None when the optimizer
    structure is unrecognized."""
    n_states = len(states[0]) if states else 0
    field_order = ("mu", "nu") if n_states == 2 else ("trace",)
    trees = [jax.tree_util.tree_unflatten(
        params_treedef, [np.asarray(s[i], np.float32) for s in states])
        for i in range(n_states)]
    hit = {"n": 0}

    def rebuild(node):
        if hasattr(node, "_fields"):
            upd = {}
            for i, f in enumerate(field_order):
                if f in node._fields:
                    cur_leaves = jax.tree_util.tree_leaves(getattr(node, f))
                    if len(cur_leaves) == len(states) and all(
                            np.shape(a) == np.shape(b) for a, b in
                            zip(cur_leaves,
                                jax.tree_util.tree_leaves(trees[i]))):
                        upd[f] = trees[i]
            if "count" in node._fields and upd:
                upd["count"] = jnp.asarray(step_count,
                                           np.asarray(node.count).dtype)
            if upd:
                hit["n"] += 1
                return node._replace(**upd)
            return node._replace(**{
                f: rebuild(getattr(node, f)) for f in node._fields
                if isinstance(getattr(node, f), tuple)})
        if isinstance(node, tuple):
            return type(node)(rebuild(v) for v in node)
        if isinstance(node, list):
            return [rebuild(v) for v in node]
        return node

    out = rebuild(opt_state)
    return out if hit["n"] else None


def _adopt_error_feedback(opt_state, fallback_tree):
    """Mesh-portable comm_compression residual adoption (cross-topology
    resume): mine the checkpoint's ``error_feedback`` subtree out of the
    metadata-shaped fallback restore (orbax renders the
    ``CommCompressState`` NamedTuple as a dict) and fit each bucket's
    residual to the live engine's layout — bit-exact when the replica
    world matches, mean-preserving worker reshard
    (``compress.reshard_error_feedback``) when it changed. The bucket
    MEMBERSHIP is a pure function of model + config, while the payload
    padding moves with the world — a width mismatch is fitted losslessly
    (the pad tail carries exactly-zero residual); only a structurally
    unrecognizable tree (bucket count / rank mismatch — a different model
    or config) leaves the fresh zero residuals in place, logged by the
    caller — never a crash. Returns the updated opt_state, or None when
    there is nothing to adopt."""
    try:
        from deepspeed_tpu.comm.compress import (CommCompressState, TensorEF,
                                                 reshard_error_feedback)
    except Exception:           # jax-less / partial install: nothing to do
        return None
    if not isinstance(opt_state, CommCompressState) \
            or not opt_state.error_feedback:
        return None
    found: list = []
    _extract_named_subtrees(fallback_tree, "error_feedback", found)
    for cand in found:
        buckets = list(cand) if isinstance(cand, (list, tuple)) else None
        if buckets is None or len(buckets) != len(opt_state.error_feedback):
            continue
        new_ef = []
        for saved, cur in zip(buckets, opt_state.error_feedback):
            if isinstance(saved, dict):
                worker, server = saved.get("worker"), saved.get("server")
            else:
                worker = getattr(saved, "worker", None)
                server = getattr(saved, "server", None)
            w_cur, n_pad = (int(cur.worker.shape[0]),
                            int(cur.worker.shape[1]))
            if worker is None or np.ndim(worker) != 2:
                new_ef = None   # different bucket plan: keep fresh zeros
                break
            # stay on HOST (the moment-mining idiom): the caller's single
            # sharded device_put distributes the result — materializing
            # [W, n_pad] fp32 per bucket on one device first would spike
            # HBM by the full replica-world multiple during load
            worker = np.asarray(jax.device_get(worker), np.float32)
            if worker.shape[1] != n_pad:
                # n_pad is padded to world*chunk, so a world change can
                # move it even for the SAME bucket (same leaves, same n).
                # The payload occupies [:n] in both layouts and the pad
                # tail carries an exactly-zero residual (quantizing zeros
                # is exact), so pad/truncate is lossless
                fit = np.zeros((worker.shape[0], n_pad), np.float32)
                m = min(int(worker.shape[1]), n_pad)
                fit[:, :m] = worker[:, :m]
                worker = fit
            if int(worker.shape[0]) == w_cur and server is not None \
                    and tuple(np.shape(server)) == tuple(cur.server.shape):
                # same replica world: residuals restore bit-identically
                new_ef.append(TensorEF(
                    worker=worker,
                    server=np.asarray(jax.device_get(server), np.float32)))
            else:
                # changed world: THE shared mean-preserving rule, on host
                # (xp=np) so nothing materializes on one device
                new_ef.append(reshard_error_feedback(
                    TensorEF(worker=worker, server=None), w_cur, xp=np))
        if new_ef is not None:
            return opt_state._replace(error_feedback=tuple(new_ef))
    return None


def _respread_error_feedback(engine, opt_state, provenance):
    """comm_compression residuals across a replica-world change on the
    DIRECT restore path: orbax fits the checkpoint's [W_old, n_pad] state
    to the new leading dim by row-prefix (zero-pad on grow, truncate on
    shrink — verified behavior), which under-weights the surviving
    residual mass. Re-spread the surviving rows' mean to every new
    participant — the mean over the new group equals the mean over the
    survivors, i.e. the correction mass the next reduction repays — and
    restart the server residuals at zero (their chunking changed with the
    world). The saved replica world comes from checkpoint provenance;
    returns the fixed opt_state or None when nothing needs doing."""
    try:
        from deepspeed_tpu.comm.compress import (CommCompressState,
                                                 reshard_error_feedback)
    except Exception:
        return None
    comp = getattr(engine, "_comm_compress", None)
    if comp is None or not isinstance(opt_state, CommCompressState) \
            or not opt_state.error_feedback:
        return None
    saved_mesh = (provenance or {}).get("mesh") or {}
    if not saved_mesh:
        return None
    w_old = 1
    for ax in comp.axes:
        w_old *= int(saved_mesh.get(ax, 1) or 1)
    w_cur = comp.world
    if w_old == w_cur:
        return None                # same replica world: rows are exact
    surviving = max(min(w_old, w_cur), 1)
    new_ef = tuple(
        reshard_error_feedback(ef, w_cur, surviving=surviving)
        for ef in opt_state.error_feedback)
    return opt_state._replace(error_feedback=new_ef)


def _offload_sidecar_path(path: str) -> Optional[str]:
    """This process's offload moment sidecar, falling back to proc0's when
    the checkpoint was saved at a SMALLER world (grown-world resume: a rank
    beyond the saving world has no file of its own; the moment arrays are
    full-shape, so every rank grafting proc0's beats some ranks silently
    resetting to zero — divergent optimizer state across ranks)."""
    own = os.path.join(path, f"offload_state_proc{jax.process_index()}.npz")
    if os.path.exists(own):
        return own
    if jax.process_index() != 0:
        p0 = os.path.join(path, "offload_state_proc0.npz")
        if os.path.exists(p0):
            logger.warning(
                f"checkpoint has no offload sidecar for process "
                f"{jax.process_index()} (saved at a smaller world); using "
                f"proc0's moments")
            return p0
    return None


def _snapshot_sidecars(engine, client_state):
    """Capture everything outside the orbax composite at save time."""
    offload = getattr(engine, "_offload", None)
    offload_sd = None
    if offload is not None:
        sd = offload.state_dict()
        offload_sd = {"step_count": int(sd["step_count"]),
                      "states": [[np.array(s, copy=True) for s in states]
                                 for states in sd["states"]]}
    compressor = getattr(engine, "compressor", None)
    comp_sd = None
    # only process 0 writes sidecars — don't copy masks anywhere else
    if compressor is not None and jax.process_index() == 0:
        sd = compressor.state_dict()
        comp_sd = {"training_steps": sd["training_steps"],
                   "mask_frozen": sd["mask_frozen"],
                   "masks": {m: {k: np.array(v, copy=True)
                                 for k, v in d.items()}
                             for m, d in sd["masks"].items()}}
    meta = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "zero_stage": engine.zero_stage,
        "mesh_shape": dict(engine.mesh.shape),
        "client_state": client_state or {},
    }
    try:
        meta["provenance"] = checkpoint_provenance(engine)
    except Exception:
        # a provenance failure must never lose the checkpoint itself;
        # the resulting tag simply resumes like a legacy (pre-provenance)
        # checkpoint
        logger.exception("checkpoint: provenance capture failed")
    return {"offload": offload_sd, "compression": comp_sd, "meta": meta}


def _write_sidecars_and_commit(save_dir, tag, path, sidecars):
    """Persist the point-in-time sidecar snapshot, fsync everything, write
    the integrity manifest, and only THEN commit the ``latest`` tag (atomic
    tmp+rename). The commit marker is the last durable write, so a host
    crash at any point leaves either no commit (tag ignored on resume) or a
    fully-verifiable checkpoint — never a torn-but-committed one."""
    offload_sd = sidecars["offload"]
    if offload_sd is not None:
        # host optimizer moments, one file per process (process-local shards)
        npz_path = os.path.join(
            path, f"offload_state_proc{jax.process_index()}.npz")
        np.savez(
            npz_path,
            step_count=np.int64(offload_sd["step_count"]),
            **{f"s_{i}_{j}": s
               for i, states in enumerate(offload_sd["states"])
               for j, s in enumerate(states)})
        _fsync_file(npz_path)

    comp_sd = sidecars["compression"]
    if comp_sd is not None and jax.process_index() == 0:
        # pruning masks must survive resume: refreezing from restored (or fresh
        # random) weights would silently change the sparsity pattern
        arrays = {f"mask::{m}::{name}": arr
                  for m, d in comp_sd["masks"].items()
                  for name, arr in d.items()}
        comp_path = os.path.join(path, "compression_state.npz")
        np.savez(comp_path,
                 training_steps=np.int64(comp_sd["training_steps"]),
                 mask_frozen=np.array(json.dumps(comp_sd["mask_frozen"])),
                 **arrays)
        _fsync_file(comp_path)

    if jax.process_index() == 0:
        meta_path = os.path.join(path, "ds_meta.json")
        with open(meta_path, "w") as f:
            json.dump(sidecars["meta"], f, indent=2, default=str)
            f.flush()
            os.fsync(f.fileno())
        # manifest covers every file process 0 can vouch for at commit time;
        # on shared storage other processes' per-process sidecars may still
        # be mid-write (no barrier here), so they are excluded rather than
        # risk recording a partial checksum that brands the tag torn
        own = f"offload_state_proc{jax.process_index()}.npz"
        write_manifest(
            path,
            extra_meta={"tag": tag,
                        "global_steps": sidecars["meta"].get("global_steps")},
            exclude=(None if jax.process_count() == 1 else
                     (lambda name: name.startswith("offload_state_proc")
                      and name != own)))
        _commit_latest(save_dir, tag)
    else:
        _fsync_dir(path)
    log_dist(f"saved checkpoint {path}", ranks=[0])


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           verify_integrity: bool = True,
                           strict_provenance: bool = True):
    wait_pending_checkpoint(engine)      # an in-flight async save must commit
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            log_dist(f"no '{LATEST_FILE}' file in {load_dir}; nothing restored", ranks=[0])
            return None, {}
    path = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint not found: {path}")
    if verify_integrity and verify_manifest(path):
        # raises CheckpointCorruptionError on any mismatch — a torn
        # checkpoint is never restored (resume_from_latest catches this and
        # falls back to the newest clean tag); manifest-less (legacy)
        # checkpoints load unverified
        log_dist(f"checkpoint integrity verified: {path}", ranks=[0])

    # provenance gate BEFORE any array read: model/sampler incompatibility
    # is a classified error here; a mesh/world/zero-tier change is logged
    # as a mesh-portable resume (and stamped on the dstrace timeline)
    meta_path = os.path.join(path, "ds_meta.json")
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    provenance = check_provenance(engine, meta, path,
                                  strict=strict_provenance)

    state = engine.state
    offload = getattr(engine, "_offload", None)
    param_offload = getattr(engine, "_param_offload", None)
    # Restore with the *current* engine shardings — a mesh/world-size change between
    # save and load reshapes automatically (the UCP capability, built in).
    # Checkpointed params are always fp32 (masters); under offload the live
    # device params are compute-dtype, so the target dtype is forced to fp32.
    if param_offload is not None:
        # params never materialize on device: restore straight to host arrays
        # (no sharding in the target -> orbax returns numpy)
        params_target = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, np.float32),
            param_offload.masters_tree())
    else:
        params_target = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                x.shape, np.float32 if offload is not None else x.dtype,
                sharding=s),
            state.params, engine.param_shardings)
    target = {
        "params": params_target,
        "opt_state": jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            state.opt_state, engine.opt_state_shardings),
        # explicit replicated sharding: restoring without one only works when
        # the saved topology matches (orbax falls back to the sharding file,
        # which references the SAVING processes' devices)
        "scalars": jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype,
                sharding=jax.sharding.NamedSharding(
                    engine.mesh, jax.sharding.PartitionSpec())),
            {
                "step": state.step,
                "loss_scale": state.loss_scale.scale,
                "good_steps": state.loss_scale.good_steps,
                "hysteresis": state.loss_scale.hysteresis,
                "skipped_steps": state.skipped_steps,
            }),
    }
    ckptr = ocp.StandardCheckpointer()
    adopted_opt = None       # cross-tier optax state, mined for moments below
    opt_fallback = False     # opt_state came from the metadata fallback
    fallback_opt_tree = None  # the checkpoint's own-shaped opt tree (host)
    try:
        try:
            restored = ckptr.restore(path, target)
        except (ValueError, KeyError):
            # ValueError: saved opt_state tree shape mismatches the target;
            # KeyError: the target asks for opt_state keys the checkpoint
            # never stored (e.g. an offload checkpoint's empty tuple vs a
            # live optax tree) — both mean "cross-tier/topology opt_state",
            # same fallback
            opt_fallback = True
            # cross-topology/tier load: the saved opt_state tree (e.g. an
            # optax state vs an offload engine's empty tuple, or vice versa
            # after the ladder escalated on a shrink) need not match this
            # engine — rebuild that part of the target host-side from the
            # checkpoint's own metadata; what to do with the restored tree
            # is decided below
            ckpt_meta = ckptr.metadata(path)
            opt_meta = ckpt_meta["opt_state"] if isinstance(ckpt_meta, dict) \
                else getattr(ckpt_meta, "item_metadata",
                             ckpt_meta)["opt_state"]
            target["opt_state"] = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype),
                opt_meta)
            restored = ckptr.restore(path, target)
            # keep the checkpoint-shaped host tree: the moment mining below
            # AND the comm_compression error-feedback adoption read it
            fallback_opt_tree = restored["opt_state"]
            if load_optimizer_states and offload is not None:
                # tier escalation (optax -> host offload): the checkpoint's
                # optax moments become the host kernel's moment buffers
                adopted_opt = restored["opt_state"]
            elif load_optimizer_states:
                # tier de-escalation (offload -> optax): the per-process npz
                # sidecar carries the moments; grafted into the fresh optax
                # state after the params land (below). If the checkpoint HAD
                # a real optax state but it still mismatched this engine's
                # (different optimizer), classify instead of shape-crashing.
                if jax.tree_util.tree_leaves(restored["opt_state"]):
                    mined, mined_count = _extract_moments(
                        restored["opt_state"],
                        [tuple(x.shape)
                         for x in jax.tree_util.tree_leaves(params_target)],
                        n_states=2)
                    if mined is None:
                        raise CheckpointProvenanceError(
                            f"checkpoint {path}: saved optimizer state does "
                            f"not match this engine's optimizer structure "
                            f"and its moments are unrecognizable; resume "
                            f"with load_optimizer_states=False to restore "
                            f"weights only") from None
                    adopted_opt = ("mined", mined, mined_count)
            restored["opt_state"] = state.opt_state
    finally:
        ckptr.close()

    from deepspeed_tpu.runtime.engine import EngineState
    from deepspeed_tpu.runtime.precision import LossScaleState
    sc = restored["scalars"]
    restored_params = restored["params"]

    if offload is not None:
        # Resync the host tier: masters take the restored weights; moments come
        # from the per-process state file (reset if the checkpoint has none, e.g.
        # saved by a non-offload config). Device params become fresh shadows —
        # without this resync the next step would revert to stale masters.
        masters = [np.asarray(jax.device_get(p), np.float32)
                   for p in jax.tree.leaves(restored_params)]
        npz_path = _offload_sidecar_path(path) if load_optimizer_states \
            else None
        if npz_path is not None:
            data = np.load(npz_path)
            n_states = offload.n_states
            states = [[data[f"s_{i}_{j}"] for j in range(n_states)]
                      for i in range(len(masters))]
            offload.load_state_dict({"step_count": int(data["step_count"]),
                                     "masters": masters, "states": states})
        elif load_optimizer_states and adopted_opt is not None:
            # tier escalation resume (the shrink ladder moved the optimizer
            # to host): adopt the checkpoint's optax moments as the host
            # kernel's moment buffers — optimizer state survives the tier
            # change instead of resetting
            states, step_count = _extract_moments(
                adopted_opt, [m.shape for m in masters], offload.n_states)
            if states is not None:
                offload.load_state_dict({"step_count": step_count,
                                         "masters": masters,
                                         "states": states})
                log_dist(f"offload: adopted optimizer moments from the "
                         f"checkpoint's optax state (tier escalation, "
                         f"step_count={step_count})", ranks=[0])
            else:
                log_dist("offload: checkpoint's optax state structure "
                         "unrecognized; moments reset to zero", ranks=[0])
                offload.set_masters(masters, reset_moments=True)
        else:
            if load_optimizer_states:
                log_dist("offload: checkpoint has no host optimizer state; "
                         "moments reset to zero", ranks=[0])
            offload.set_masters(masters, reset_moments=True)
        if param_offload is not None:
            # streamed params: refresh the host compute store (+ nvme files)
            # from the restored masters; device params stay empty
            param_offload.sync_store()
            restored_params = state.params
        else:
            shadow = offload.shadows(np.dtype(engine.compute_dtype).name)
            restored_params = jax.device_put(
                jax.tree_util.tree_unflatten(engine._params_treedef, shadow),
                engine.param_shardings)

    if load_optimizer_states and offload is None:
        # tier de-escalation resume (host-offload checkpoint onto an optax
        # engine, e.g. the ladder relaxing after a regrow): graft the
        # per-process moment sidecar — or moments mined from a mismatched
        # optax state — into this engine's live optimizer structure
        mined = None
        if isinstance(adopted_opt, tuple) and adopted_opt[0] == "mined":
            mined = (adopted_opt[1], adopted_opt[2])
        elif opt_fallback:
            npz_path = _offload_sidecar_path(path)
            if npz_path is not None:
                data = np.load(npz_path)
                n_leaves = len(jax.tree_util.tree_leaves(restored_params))
                n_states = len([k for k in data.files
                                if k.startswith("s_0_")])
                if n_states:
                    mined = ([[data[f"s_{i}_{j}"] for j in range(n_states)]
                              for i in range(n_leaves)],
                             int(data["step_count"]))
        if mined is not None:
            states, step_count = mined
            grafted = _inject_moments_into_optax(
                engine.state.opt_state,
                jax.tree_util.tree_structure(restored_params),
                states, step_count)
            if grafted is not None:
                restored["opt_state"] = jax.device_put(
                    grafted, engine.opt_state_shardings)
                log_dist(f"optimizer moments grafted from the checkpoint's "
                         f"host-offload tier (step_count={step_count})",
                         ranks=[0])
            else:
                log_dist("WARNING: checkpoint optimizer moments do not fit "
                         "this engine's optimizer structure; optimizer "
                         "state starts fresh", ranks=[0])

    final_opt = restored["opt_state"] if load_optimizer_states \
        else state.opt_state
    if load_optimizer_states:
        # comm_compression residuals across a topology change must survive
        # the elastic reshard instead of silently resetting / zero-padding
        adopted_ef = None
        if fallback_opt_tree is not None:
            # structure changed (cross-tier / toggled group): mine the
            # error_feedback subtree out of the checkpoint-shaped tree
            adopted_ef = _adopt_error_feedback(final_opt, fallback_opt_tree)
        else:
            # direct restore succeeded: orbax fits the [W, n_pad] state to
            # a changed replica world by row-prefix (zero-pad on grow,
            # truncate on shrink) — re-spread the surviving rows' mean
            adopted_ef = _respread_error_feedback(engine, final_opt,
                                                  provenance)
        if adopted_ef is not None:
            final_opt = jax.device_put(adopted_ef,
                                       engine.opt_state_shardings)
            log_dist("comm_compression: error-feedback residuals adopted "
                     "from the checkpoint (resharded to the current "
                     "replica world)", ranks=[0])
        elif fallback_opt_tree is not None \
                and getattr(engine, "_comm_compress", None) is not None:
            # never silent: the fallback restore ran but the checkpoint's
            # EF subtree was absent or its bucket plan unrecognizable
            log_dist("comm_compression: checkpoint carries no adoptable "
                     "error-feedback residuals; starting fresh (moments "
                     "unaffected)", ranks=[0])
    engine.state = EngineState(
        step=sc["step"],
        params=restored_params,
        opt_state=final_opt,
        loss_scale=LossScaleState(sc["loss_scale"], sc["good_steps"], sc["hysteresis"]),
        skipped_steps=sc["skipped_steps"],
    )

    compressor = getattr(engine, "compressor", None)
    comp_path = os.path.join(path, "compression_state.npz")
    if compressor is not None and os.path.exists(comp_path):
        data = np.load(comp_path)
        masks: Dict[str, Dict[str, np.ndarray]] = {}
        for key in data.files:
            if key.startswith("mask::"):
                _, method, name = key.split("::", 2)
                masks.setdefault(method, {})[name] = data[key]
        # methods with no saved masks still need their dict entries
        for method in compressor._masks:
            masks.setdefault(method, {})
        compressor.load_state_dict({
            "training_steps": int(data["training_steps"]),
            "mask_frozen": json.loads(str(data["mask_frozen"])),
            "masks": masks,
        })

    client_state: Dict[str, Any] = {}
    if meta:
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        engine.micro_steps = meta.get("micro_steps", 0)
        client_state = meta.get("client_state", {})
    if provenance and provenance.get("rng"):
        # resume the rng stream exactly where the save left it (replicated
        # host state — world-size independent), so dropout-style rngs are
        # deterministic across preempt/shrink/regrow boundaries
        try:
            _restore_rng(engine, provenance["rng"])
        except Exception:
            logger.exception("checkpoint: rng restore failed; the engine "
                             "keeps its init-seeded key")
    log_dist(f"loaded checkpoint {path}", ranks=[0])
    return path, client_state
