"""Checkpoint save/load — single logical sharded checkpoint, reshape-on-load.

Reference analogs:
- ``runtime/engine.py:3109 save_checkpoint`` / ``:2763 load_checkpoint`` (per-rank
  ``mp_rank_XX_model_states.pt`` + per-dp-rank optim shards, ``latest`` tag file)
- ``runtime/checkpoint_engine/checkpoint_engine.py`` (pluggable engine ABC)
- ``deepspeed/checkpoint/ds_to_universal.py`` universal checkpoint (per-parameter
  atomic files enabling TP/PP/DP reshape on resume)

TPU-native design (SURVEY.md §5.4): orbax/tensorstore OCDBT writes ONE logical
checkpoint where every array is stored parameter-atomically regardless of its runtime
sharding — so *every* checkpoint is a "universal checkpoint": loading onto a different
mesh/world size just reads each array with the new sharding. The offline
``ds_to_universal`` converter is unnecessary by construction.

The ``latest`` tag-file protocol is kept for API parity.
"""

import json
import os
import zlib
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"
MANIFEST_FILE = "ds_manifest.json"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed its integrity-manifest verification: a listed
    file is missing or its checksum no longer matches — the checkpoint is
    torn and must never be restored."""


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


# ---------------------------------------------------------------------------
# durability primitives: fsync + integrity manifest + atomic commit
# ---------------------------------------------------------------------------
def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Flush directory entries (the rename/create records) to disk; no-op on
    platforms whose directory fds reject fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _file_crc(path: str, chunk: int = 1 << 20):
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            size += len(buf)
    return size, crc


def write_manifest(path: str, extra_meta: Optional[Dict[str, Any]] = None,
                   exclude=None) -> Dict[str, Any]:
    """Walk the checkpoint dir, checksum every file (crc32 + size), persist
    ``ds_manifest.json`` and fsync it + every hashed file. Written strictly
    BEFORE the ``latest`` commit: a committed tag therefore always carries a
    verifiable manifest, and a crash mid-save leaves a tag that simply never
    commits. ``exclude(filename) -> bool`` skips files another process may
    still be writing (no cross-process barrier exists here — checksumming a
    peer's in-flight sidecar would brand a good checkpoint torn forever)."""
    files: Dict[str, Dict[str, int]] = {}
    for root, _, names in os.walk(path):
        for name in sorted(names):
            if name == MANIFEST_FILE:
                continue
            if exclude is not None and exclude(name):
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            size, crc = _file_crc(full)
            files[rel] = {"size": size, "crc32": crc}
            _fsync_file(full)
    manifest = {"version": 1, "files": files, "meta": extra_meta or {}}
    mpath = os.path.join(path, MANIFEST_FILE)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(path)
    return manifest


def verify_manifest(path: str) -> bool:
    """Re-checksum a checkpoint against its manifest. Returns True when the
    manifest exists and every listed file matches; False for a legacy
    (manifest-less) checkpoint; raises ``CheckpointCorruptionError`` on any
    missing file or checksum mismatch."""
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(mpath):
        return False
    with open(mpath) as f:
        manifest = json.load(f)
    for rel, want in manifest.get("files", {}).items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            raise CheckpointCorruptionError(
                f"checkpoint {path}: manifest file missing: {rel}")
        size, crc = _file_crc(full)
        if size != want["size"] or crc != want["crc32"]:
            raise CheckpointCorruptionError(
                f"checkpoint {path}: checksum mismatch for {rel} "
                f"(size {size} vs {want['size']}, crc {crc} vs {want['crc32']})")
    return True


def is_committed(save_dir: str, tag: str, verify: bool = True) -> bool:
    """True when ``tag`` is a fully-committed, integrity-clean checkpoint
    (manifest verification failures count as not-committed rather than
    raising — callers use this to pick a fallback tag)."""
    path = _ckpt_dir(save_dir, tag)
    if not os.path.isdir(path) or not os.path.exists(
            os.path.join(path, "ds_meta.json")):
        return False
    if not verify:
        return True
    try:
        verify_manifest(path)
    except CheckpointCorruptionError as e:
        logger.warning(f"checkpoint integrity: {e}")
        return False
    return True


def read_latest_tag(save_dir: str) -> Optional[str]:
    """The tag the ``latest`` pointer names, or None — the single reader for
    the pointer protocol (resume discovery, pruning, env_report, and the
    load path all go through here)."""
    latest = os.path.join(os.path.abspath(save_dir), LATEST_FILE)
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        return f.read().strip() or None


def _commit_latest(save_dir: str, tag: str) -> None:
    """Atomically publish ``tag`` as the latest committed checkpoint:
    tmp-file + fsync + rename + directory fsync, so a host crash at any
    instant leaves either the old pointer or the new one — never a torn
    ``latest``."""
    save_dir = os.path.abspath(save_dir)
    tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
    with open(tmp, "w") as f:
        f.write(tag)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(save_dir, LATEST_FILE))
    _fsync_dir(save_dir)


def wait_pending_checkpoint(engine) -> None:
    """Block until a previous async save (if any) has fully committed, and
    re-raise any error the background finalizer hit (reference: nebula async
    checkpoint engine's commit barrier)."""
    t = getattr(engine, "_pending_ckpt", None)
    if t is not None:
        t.join()
        engine._pending_ckpt = None
        err = getattr(engine, "_pending_ckpt_error", None)
        if err is not None:
            engine._pending_ckpt_error = None
            raise RuntimeError("async checkpoint save failed") from err


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[Dict[str, Any]] = None,
                           async_save: Optional[bool] = None) -> str:
    """``async_save`` (default: engine config ``checkpoint.async_save``):
    orbax fetches the arrays synchronously (so the training step may donate
    buffers immediately after return) and persists + commits the ``latest``
    tag from a background thread — the reference's Nebula-style async engine
    (``runtime/checkpoint_engine/nebula_checkpoint_engine.py``)."""
    if async_save is None:
        async_save = bool(getattr(engine.config, "checkpoint_config",
                                  None) and
                          engine.config.checkpoint_config.async_save)
    wait_pending_checkpoint(engine)          # one in flight at a time
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    path = _ckpt_dir(save_dir, tag)
    state = engine.state
    offload = getattr(engine, "_offload", None)
    params_to_save = state.params
    if offload is not None:
        # Under offload the authoritative weights are the fp32 host masters
        # (device params are compute-dtype shadows) — save those so the
        # checkpoint stays fp32 regardless of offload config.
        params_to_save = jax.tree_util.tree_unflatten(
            engine._params_treedef, offload.masters())
    composite = {
        "params": params_to_save,
        "opt_state": state.opt_state,
        "scalars": {
            "step": state.step,
            "loss_scale": state.loss_scale.scale,
            "good_steps": state.loss_scale.good_steps,
            "hysteresis": state.loss_scale.hysteresis,
            "skipped_steps": state.skipped_steps,
        },
    }
    ckptr = ocp.StandardCheckpointer()
    # orbax's save is async by design: device->host fetch happens before it
    # returns, disk persistence + atomic rename happen in the background
    ckptr.save(path, composite, force=True)

    # sidecar state (host optimizer moments, compression masks, step counters)
    # mutates every train_batch — snapshot it NOW so async persistence commits
    # a consistent point-in-time checkpoint
    sidecars = _snapshot_sidecars(engine, client_state)

    def _finalize():
        try:
            ckptr.wait_until_finished()
            ckptr.close()
            _write_sidecars_and_commit(save_dir, tag, path, sidecars)
        except BaseException as e:
            if async_save:                   # surfaced by wait_pending_checkpoint
                engine._pending_ckpt_error = e
            raise

    if async_save:
        import threading
        # non-daemon: a save in flight at interpreter exit completes instead
        # of silently losing the run's final checkpoint
        t = threading.Thread(target=_finalize, daemon=False,
                             name="dstpu-async-ckpt")
        t.start()
        engine._pending_ckpt = t
        log_dist(f"async checkpoint scheduled: {path}", ranks=[0])
        return path
    _finalize()
    return path


def _snapshot_sidecars(engine, client_state):
    """Capture everything outside the orbax composite at save time."""
    offload = getattr(engine, "_offload", None)
    offload_sd = None
    if offload is not None:
        sd = offload.state_dict()
        offload_sd = {"step_count": int(sd["step_count"]),
                      "states": [[np.array(s, copy=True) for s in states]
                                 for states in sd["states"]]}
    compressor = getattr(engine, "compressor", None)
    comp_sd = None
    # only process 0 writes sidecars — don't copy masks anywhere else
    if compressor is not None and jax.process_index() == 0:
        sd = compressor.state_dict()
        comp_sd = {"training_steps": sd["training_steps"],
                   "mask_frozen": sd["mask_frozen"],
                   "masks": {m: {k: np.array(v, copy=True)
                                 for k, v in d.items()}
                             for m, d in sd["masks"].items()}}
    meta = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "zero_stage": engine.zero_stage,
        "mesh_shape": dict(engine.mesh.shape),
        "client_state": client_state or {},
    }
    return {"offload": offload_sd, "compression": comp_sd, "meta": meta}


def _write_sidecars_and_commit(save_dir, tag, path, sidecars):
    """Persist the point-in-time sidecar snapshot, fsync everything, write
    the integrity manifest, and only THEN commit the ``latest`` tag (atomic
    tmp+rename). The commit marker is the last durable write, so a host
    crash at any point leaves either no commit (tag ignored on resume) or a
    fully-verifiable checkpoint — never a torn-but-committed one."""
    offload_sd = sidecars["offload"]
    if offload_sd is not None:
        # host optimizer moments, one file per process (process-local shards)
        npz_path = os.path.join(
            path, f"offload_state_proc{jax.process_index()}.npz")
        np.savez(
            npz_path,
            step_count=np.int64(offload_sd["step_count"]),
            **{f"s_{i}_{j}": s
               for i, states in enumerate(offload_sd["states"])
               for j, s in enumerate(states)})
        _fsync_file(npz_path)

    comp_sd = sidecars["compression"]
    if comp_sd is not None and jax.process_index() == 0:
        # pruning masks must survive resume: refreezing from restored (or fresh
        # random) weights would silently change the sparsity pattern
        arrays = {f"mask::{m}::{name}": arr
                  for m, d in comp_sd["masks"].items()
                  for name, arr in d.items()}
        comp_path = os.path.join(path, "compression_state.npz")
        np.savez(comp_path,
                 training_steps=np.int64(comp_sd["training_steps"]),
                 mask_frozen=np.array(json.dumps(comp_sd["mask_frozen"])),
                 **arrays)
        _fsync_file(comp_path)

    if jax.process_index() == 0:
        meta_path = os.path.join(path, "ds_meta.json")
        with open(meta_path, "w") as f:
            json.dump(sidecars["meta"], f, indent=2, default=str)
            f.flush()
            os.fsync(f.fileno())
        # manifest covers every file process 0 can vouch for at commit time;
        # on shared storage other processes' per-process sidecars may still
        # be mid-write (no barrier here), so they are excluded rather than
        # risk recording a partial checksum that brands the tag torn
        own = f"offload_state_proc{jax.process_index()}.npz"
        write_manifest(
            path,
            extra_meta={"tag": tag,
                        "global_steps": sidecars["meta"].get("global_steps")},
            exclude=(None if jax.process_count() == 1 else
                     (lambda name: name.startswith("offload_state_proc")
                      and name != own)))
        _commit_latest(save_dir, tag)
    else:
        _fsync_dir(path)
    log_dist(f"saved checkpoint {path}", ranks=[0])


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           verify_integrity: bool = True):
    wait_pending_checkpoint(engine)      # an in-flight async save must commit
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            log_dist(f"no '{LATEST_FILE}' file in {load_dir}; nothing restored", ranks=[0])
            return None, {}
    path = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint not found: {path}")
    if verify_integrity and verify_manifest(path):
        # raises CheckpointCorruptionError on any mismatch — a torn
        # checkpoint is never restored (resume_from_latest catches this and
        # falls back to the newest clean tag); manifest-less (legacy)
        # checkpoints load unverified
        log_dist(f"checkpoint integrity verified: {path}", ranks=[0])

    state = engine.state
    offload = getattr(engine, "_offload", None)
    param_offload = getattr(engine, "_param_offload", None)
    # Restore with the *current* engine shardings — a mesh/world-size change between
    # save and load reshapes automatically (the UCP capability, built in).
    # Checkpointed params are always fp32 (masters); under offload the live
    # device params are compute-dtype, so the target dtype is forced to fp32.
    if param_offload is not None:
        # params never materialize on device: restore straight to host arrays
        # (no sharding in the target -> orbax returns numpy)
        params_target = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, np.float32),
            param_offload.masters_tree())
    else:
        params_target = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                x.shape, np.float32 if offload is not None else x.dtype,
                sharding=s),
            state.params, engine.param_shardings)
    target = {
        "params": params_target,
        "opt_state": jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            state.opt_state, engine.opt_state_shardings),
        # explicit replicated sharding: restoring without one only works when
        # the saved topology matches (orbax falls back to the sharding file,
        # which references the SAVING processes' devices)
        "scalars": jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype,
                sharding=jax.sharding.NamedSharding(
                    engine.mesh, jax.sharding.PartitionSpec())),
            {
                "step": state.step,
                "loss_scale": state.loss_scale.scale,
                "good_steps": state.loss_scale.good_steps,
                "hysteresis": state.loss_scale.hysteresis,
                "skipped_steps": state.skipped_steps,
            }),
    }
    ckptr = ocp.StandardCheckpointer()
    try:
        restored = ckptr.restore(path, target)
    except ValueError:
        if load_optimizer_states:
            ckptr.close()
            raise
        # cross-topology/tier load without optimizer state: the saved
        # opt_state tree (e.g. a zero-3 optax state vs a param-offload
        # engine's empty tuple) need not match this engine — rebuild that
        # part of the target from the checkpoint's own metadata and discard
        # it after restore
        meta = ckptr.metadata(path)
        opt_meta = meta["opt_state"] if isinstance(meta, dict) else \
            getattr(meta, "item_metadata", meta)["opt_state"]
        target["opt_state"] = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype),
            opt_meta)
        restored = ckptr.restore(path, target)
        restored["opt_state"] = state.opt_state
    ckptr.close()

    from deepspeed_tpu.runtime.engine import EngineState
    from deepspeed_tpu.runtime.precision import LossScaleState
    sc = restored["scalars"]
    restored_params = restored["params"]

    if offload is not None:
        # Resync the host tier: masters take the restored weights; moments come
        # from the per-process state file (reset if the checkpoint has none, e.g.
        # saved by a non-offload config). Device params become fresh shadows —
        # without this resync the next step would revert to stale masters.
        masters = [np.asarray(jax.device_get(p), np.float32)
                   for p in jax.tree.leaves(restored_params)]
        npz_path = os.path.join(
            path, f"offload_state_proc{jax.process_index()}.npz")
        if load_optimizer_states and os.path.exists(npz_path):
            data = np.load(npz_path)
            n_states = offload.n_states
            states = [[data[f"s_{i}_{j}"] for j in range(n_states)]
                      for i in range(len(masters))]
            offload.load_state_dict({"step_count": int(data["step_count"]),
                                     "masters": masters, "states": states})
        else:
            if load_optimizer_states:
                log_dist("offload: checkpoint has no host optimizer state; "
                         "moments reset to zero", ranks=[0])
            offload.set_masters(masters, reset_moments=True)
        if param_offload is not None:
            # streamed params: refresh the host compute store (+ nvme files)
            # from the restored masters; device params stay empty
            param_offload.sync_store()
            restored_params = state.params
        else:
            shadow = offload.shadows(np.dtype(engine.compute_dtype).name)
            restored_params = jax.device_put(
                jax.tree_util.tree_unflatten(engine._params_treedef, shadow),
                engine.param_shardings)

    engine.state = EngineState(
        step=sc["step"],
        params=restored_params,
        opt_state=restored["opt_state"] if load_optimizer_states else state.opt_state,
        loss_scale=LossScaleState(sc["loss_scale"], sc["good_steps"], sc["hysteresis"]),
        skipped_steps=sc["skipped_steps"],
    )

    compressor = getattr(engine, "compressor", None)
    comp_path = os.path.join(path, "compression_state.npz")
    if compressor is not None and os.path.exists(comp_path):
        data = np.load(comp_path)
        masks: Dict[str, Dict[str, np.ndarray]] = {}
        for key in data.files:
            if key.startswith("mask::"):
                _, method, name = key.split("::", 2)
                masks.setdefault(method, {})[name] = data[key]
        # methods with no saved masks still need their dict entries
        for method in compressor._masks:
            masks.setdefault(method, {})
        compressor.load_state_dict({
            "training_steps": int(data["training_steps"]),
            "mask_frozen": json.loads(str(data["mask_frozen"])),
            "masks": masks,
        })

    meta_path = os.path.join(path, "ds_meta.json")
    client_state: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        engine.micro_steps = meta.get("micro_steps", 0)
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {path}", ranks=[0])
    return path, client_state
