"""Universal-checkpoint tools: inspect / consolidate / convert.

Reference analogs:
- ``deepspeed/checkpoint/ds_to_universal.py:112`` (``extract_zero_shards`` /
  ``merge_tp_slices`` — offline conversion of rank-sharded ZeRO checkpoints into
  per-parameter atomic files that any (dp, tp, pp) topology can slice on load)
- ``deepspeed/utils/zero_to_fp32.py`` (offline consolidation of ZeRO shards into
  a single fp32 state dict)
- ``deepspeed/checkpoint/universal_checkpoint.py:16`` (``load_hp_checkpoint_state``)

On TPU the engine checkpoint (checkpoint/engine.py) is *already* parameter-atomic
— orbax/tensorstore stores each array whole regardless of runtime sharding, so
every checkpoint is a universal checkpoint and ``ds_to_universal`` has no work to
do. What remains useful, and lives here:

- ``inspect_checkpoint``  — enumerate parameters/shapes/dtypes without restoring
  onto devices (metadata read only).
- ``consolidate_to_fp32`` — the ``zero_to_fp32`` analog: read the checkpoint on
  host and write one plain ``.npz`` (or per-param ``.npy`` tree) of fp32 weights
  that any framework can load, no JAX devices needed.
- ``extract_param``       — pull a single parameter array (the per-parameter
  atomic-file capability, on demand instead of ahead of time).

CLI: ``bin/dstpu_ckpt`` (inspect | consolidate).
"""

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"


def resolve_checkpoint_dir(path: str, tag: Optional[str] = None) -> str:
    """Accept either a checkpoint dir itself or a save_dir containing ``latest``."""
    path = os.path.abspath(path)
    if tag is not None:
        tagged = os.path.join(path, str(tag))
        if not os.path.isdir(tagged):
            raise FileNotFoundError(f"no checkpoint with tag {tag!r} under {path}")
        return tagged
    if (os.path.exists(os.path.join(path, "ds_meta.json"))
            or os.path.exists(os.path.join(path, "_METADATA"))
            or os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA"))):
        # a checkpoint dir itself: engine saves carry ds_meta.json; bare
        # orbax saves (e.g. PipelineEngine) are recognized by orbax markers
        return path
    latest = os.path.join(path, LATEST_FILE)
    if os.path.exists(latest):
        with open(latest) as f:
            return os.path.join(path, f.read().strip())
    raise FileNotFoundError(f"no checkpoint found under {path}")


def _restore_host(ckpt_dir: str) -> Dict[str, Any]:
    """Restore the composite tree fully replicated on host (numpy leaves)."""
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(ckpt_dir)
    ckptr.close()
    return restored


def _module_subtree(tree: Any) -> Any:
    """The module-parameter subtree of a composite checkpoint: the main
    engine stores it under 'params'; PipelineEngine stores stage-stacked
    'staged' + tied 'tied'."""
    if not isinstance(tree, dict):
        return {}
    if "params" in tree:
        return tree["params"]
    if "staged" in tree or "tied" in tree:
        return {k: tree[k] for k in ("staged", "tied") if k in tree}
    return {}


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is not None:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def inspect_checkpoint(path: str, tag: Optional[str] = None) -> Dict[str, Any]:
    """Parameter inventory + metadata — reads orbax *metadata only* (shapes and
    dtypes come from the index, no array bytes are fetched), so inspecting a
    multi-hundred-GB checkpoint is instant."""
    import orbax.checkpoint as ocp
    ckpt_dir = resolve_checkpoint_dir(path, tag)
    meta_path = os.path.join(ckpt_dir, "ds_meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    ckptr = ocp.StandardCheckpointer()
    try:
        tree_meta = ckptr.metadata(ckpt_dir)
    finally:
        ckptr.close()
    item = getattr(tree_meta, "item_metadata", tree_meta)
    tree = item if isinstance(item, dict) else getattr(item, "tree", {})
    params_meta = _flatten_meta(_module_subtree(tree))
    total = int(sum(int(np.prod(m["shape"])) for m in params_meta.values()))
    return {
        "checkpoint": ckpt_dir,
        "meta": meta,
        "num_params": total,
        "provenance": provenance_summary(meta),
        "parameters": params_meta,
    }


def provenance_summary(meta: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The rendered provenance block: saved world / mesh axes (non-trivial
    only) / zero placement / step / sampler position / rng key shape. None
    for legacy (pre-provenance) checkpoints."""
    prov = (meta or {}).get("provenance")
    if not prov:
        return None
    mesh = prov.get("mesh") or {}
    rng = prov.get("rng") or {}
    return {
        "saved_world": prov.get("world"),
        "mesh_axes": {a: s for a, s in mesh.items() if int(s or 1) != 1}
        or {"(all axes 1)": 1},
        "zero": prov.get("zero"),
        "step": (meta or {}).get("global_steps"),
        "sampler": prov.get("sampler"),
        "rng_key": {"shape": rng.get("shape"), "dtype": rng.get("dtype"),
                    "typed": rng.get("typed")},
        "batch": prov.get("batch"),
        "ledger": {k: v for k, v in (prov.get("ledger") or {}).items()
                   if k != "phase_hbm_bytes"},
    }


def compat_check(path: str, world: int, tag: Optional[str] = None
                 ) -> Dict[str, Any]:
    """Resharding-feasibility report for resuming this checkpoint at
    ``world`` workers (for a single-process checkpoint, ``world`` chips) —
    metadata only, no device or array-byte access.

    Checks: (1) the sampler contract's batch divisibility (the saved
    global batch must factor into (micro, gas, dp) at the new world — via
    the saved elasticity block when present, plain divisibility
    otherwise); (2) the analytic ledger preflight at the new per-chip
    footprint (``plan_world_config`` over the provenance's recorded
    config/param-count/HBM-limit), reporting the offload-ladder rungs a
    shrink would need."""
    ckpt_dir = resolve_checkpoint_dir(path, tag)
    with open(os.path.join(ckpt_dir, "ds_meta.json")) as f:
        meta = json.load(f)
    prov = meta.get("provenance") or {}
    out: Dict[str, Any] = {"checkpoint": ckpt_dir, "world": int(world),
                           "checks": {}, "feasible": True}
    if not prov:
        out["feasible"] = False
        out["checks"]["provenance"] = {
            "ok": False, "detail": "legacy checkpoint: no provenance block "
            "(saved before PROVENANCE_VERSION 1)"}
        return out

    batch = prov.get("batch") or {}
    tb = int(batch.get("train_batch_size", 0) or 0)
    raw = prov.get("config") or {}
    # the dp world is denominated in CHIPS, not workers — convert with the
    # SAME rule the ledger check (plan_from_provenance) uses, or the two
    # halves of this verdict would use different world units: multi-process
    # saves count device_count/process_count chips per worker; for a
    # single-process save ``world`` reads directly as a chip count
    from deepspeed_tpu.telemetry.memory import provenance_chips_per_worker
    chips_per_worker = provenance_chips_per_worker(prov)
    chips = int(world) * chips_per_worker
    # the batch divides over the DATA-PARALLEL extent only: model-parallel
    # axes (pipe/tensor/expert/sequence) are divided out of the chip count,
    # mirroring plan_world_config's mesh derivation
    model_world = 1
    for a in ("pipe", "tensor", "expert", "sequence"):
        model_world *= max(1, int((raw.get("mesh", {}) or {}).get(a, 1) or 1))
    dp_chips = max(1, chips // model_world)
    batch_ok, detail = True, (f"train_batch_size {tb} divides over "
                              f"dp world {dp_chips} ({chips} chips / "
                              f"model-parallel {model_world})")
    if (raw.get("elasticity") or {}).get("enabled"):
        from deepspeed_tpu.elasticity.elasticity import (
            ElasticityError, compute_elastic_config)
        try:
            compute_elastic_config(raw, world_size=int(world))
            detail = (f"world {world} is in the elastic config's "
                      f"compatible set (global batch {tb} invariant)")
        except ElasticityError as e:
            batch_ok, detail = False, str(e)
    elif tb and tb % dp_chips != 0:
        batch_ok = False
        detail = (f"train_batch_size {tb} not divisible by the dp world "
                  f"{dp_chips} ({world} workers x {chips_per_worker} chips "
                  f"/ model-parallel {model_world}): the sampler contract "
                  f"(global batch invariant) cannot hold")
    out["checks"]["batch"] = {"ok": batch_ok, "detail": detail}

    from deepspeed_tpu.telemetry.memory import plan_from_provenance
    plan = plan_from_provenance(prov, int(world))
    if plan is not None:
        bytes_limit = plan["verdict"]["bytes_limit"]
        out["checks"]["ledger"] = {
            "ok": plan["verdict"]["fits"] or not bytes_limit,
            "required_bytes_per_chip": plan["verdict"]["required_bytes"],
            "bytes_limit": bytes_limit,
            "escalations": plan["escalations"],
            "detail": ("fits" if plan["verdict"]["fits"] else
                       "does not fit even at the last offload rung")
            if bytes_limit else "no HBM limit recorded at save; plan only",
        }
    else:
        out["checks"]["ledger"] = {"ok": True,
                                   "detail": "no param count recorded"}
    out["feasible"] = all(c.get("ok") for c in out["checks"].values())
    return out


def _flatten_meta(tree: Any, prefix: str = "") -> Dict[str, Dict[str, Any]]:
    """Flatten an orbax metadata tree to {name: {shape, dtype}}."""
    out: Dict[str, Dict[str, Any]] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_meta(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_meta(v, f"{prefix}{i}/"))
    elif tree is not None:
        shape = list(getattr(tree, "shape", []) or [])
        dtype = str(getattr(tree, "dtype", ""))
        out[prefix[:-1]] = {"shape": shape, "dtype": dtype}
    return out


def consolidate_to_fp32(path: str, output: str, tag: Optional[str] = None,
                        include_optimizer: bool = False) -> str:
    """zero_to_fp32 analog: write a single ``.npz`` of fp32 weights.

    The reference tool must merge per-rank ``*_optim_states.pt`` shards; here the
    checkpoint is already whole-array, so consolidation is a host-side read +
    dtype cast + re-pack.
    """
    ckpt_dir = resolve_checkpoint_dir(path, tag)
    restored = _restore_host(ckpt_dir)
    arrays = {f"params/{k}": v.astype(np.float32)
              if np.issubdtype(v.dtype, np.floating) else v
              for k, v in _flatten(_module_subtree(restored)).items()}
    if include_optimizer:
        arrays.update({f"opt_state/{k}": v for k, v in
                       _flatten(restored.get("opt_state", {})).items()})
    output = os.path.abspath(output)
    os.makedirs(os.path.dirname(output) or ".", exist_ok=True)
    np.savez(output if output.endswith(".npz") else output + ".npz", **arrays)
    out_path = output if output.endswith(".npz") else output + ".npz"
    logger.info(f"consolidated {len(arrays)} tensors -> {out_path}")
    return out_path


def extract_param(path: str, param_name: str, tag: Optional[str] = None) -> np.ndarray:
    """Read one parameter (reference: universal ckpt per-param files). The name
    is validated against the metadata index first (cheap); the read itself
    restores the params tree on host — per-leaf partial restore is an orbax
    transformation detail left to a future optimization."""
    ckpt_dir = resolve_checkpoint_dir(path, tag)
    known = inspect_checkpoint(ckpt_dir)["parameters"]
    if param_name not in known:
        close = [k for k in known if param_name in k]
        raise KeyError(f"param {param_name!r} not in checkpoint; "
                       f"closest: {close[:5]}")
    return _flatten(_module_subtree(_restore_host(ckpt_dir)))[param_name]


def load_fp32_state(npz_path: str) -> Dict[str, np.ndarray]:
    """Read back a consolidated file as {name: array}."""
    data = np.load(npz_path)
    return {k[len("params/"):]: data[k] for k in data.files
            if k.startswith("params/")}


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="dstpu_ckpt",
        description="Universal checkpoint tools (inspect / consolidate to fp32)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pi = sub.add_parser("inspect", help="list parameters + metadata + "
                                        "provenance")
    pi.add_argument("path")
    pi.add_argument("--tag", default=None)
    pi.add_argument("--compat", type=int, metavar="WORLD", default=None,
                    help="additionally report resharding feasibility at "
                         "WORLD workers (chips, for a single-process "
                         "checkpoint); metadata only, no devices; exit 1 "
                         "when infeasible")
    pc = sub.add_parser("consolidate",
                        help="write a single fp32 .npz (zero_to_fp32 analog)")
    pc.add_argument("path")
    pc.add_argument("output")
    pc.add_argument("--tag", default=None)
    pc.add_argument("--include-optimizer", action="store_true")
    args = p.parse_args(argv)
    if args.cmd == "inspect":
        info = inspect_checkpoint(args.path, tag=args.tag)
        if args.compat is not None:
            info["compat"] = compat_check(args.path, args.compat,
                                          tag=args.tag)
        print(json.dumps(info, indent=2))
        if args.compat is not None and not info["compat"]["feasible"]:
            return 1
    else:
        out = consolidate_to_fp32(args.path, args.output, tag=args.tag,
                                  include_optimizer=args.include_optimizer)
        print(out)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
