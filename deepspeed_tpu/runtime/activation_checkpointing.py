"""Activation checkpointing subsystem.

Reference analog: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(1239 LoC): ``CheckpointFunction`` (custom-autograd recompute, ``:486``),
``checkpoint(fn, *args)`` (``:946``), Megatron-style ``partition_activations``
(each MP rank keeps 1/P of every saved activation, allgathered on recompute,
``:375``), ``checkpoint_in_cpu`` (saved activations parked in host RAM), and a
``CudaRNGStatesTracker`` (``:124``) so dropout replays identically on the
recompute pass.

TPU redesign — each mechanism maps to a *declarative* XLA feature instead of a
runtime hook:

- recompute             -> ``jax.checkpoint`` (autodiff-level remat)
- which values to keep  -> named remat policies (``save_only_these_names`` ...)
- partition_activations -> sharding constraints on the block inputs: under SPMD
  a saved residual annotated over (``sequence``/``tensor``) already lives
  1/P-per-device and XLA inserts the regather on the recompute path — the
  hand-written ``gather_partitioned_activations`` disappears
- checkpoint_in_cpu     -> offload policies
  (``save_and_offload_only_these_names`` with ``device -> pinned_host``); XLA
  emits the HBM<->host DMAs
- RNG tracker           -> unnecessary: JAX PRNG keys are values, so the
  recompute pass replays dropout bit-identically

Config is the ``"activation_checkpointing"`` JSON block
(``config/config.py:ActivationCheckpointingConfig``), schema-compatible with
the reference's (``deepspeed/runtime/activation_checkpointing/config.py``);
``contiguous_memory_optimization`` / ``synchronize_checkpoint_boundary`` are
accepted no-ops (XLA owns layout and there are no streams to sync).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import ActivationCheckpointingConfig


def resolve_policy(cfg: ActivationCheckpointingConfig):
    """Build the jax.checkpoint policy the config asks for. cpu_checkpointing
    keeps the tagged residuals but parks them in pinned host RAM (reference
    ``checkpoint_in_cpu``: ``copy_to_device(..., 'cpu')`` at ``:527``); here
    the offload is a remat policy and XLA schedules the DMAs."""
    pols = jax.checkpoint_policies
    if cfg.cpu_checkpointing:
        return pols.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(cfg.saved_names),
            offload_src="device", offload_dst="pinned_host")
    if cfg.policy == "save_only_names":
        return pols.save_only_these_names(*cfg.saved_names)
    named = {
        "nothing_saveable": pols.nothing_saveable,
        "everything_saveable": pols.everything_saveable,
        "dots_saveable": pols.dots_saveable,
        "dots_with_no_batch_dims_saveable": pols.dots_with_no_batch_dims_saveable,
    }
    if cfg.policy not in named:
        raise ValueError(f"unknown activation checkpointing policy "
                         f"{cfg.policy!r}; one of {sorted(named)} or "
                         "'save_only_names'")
    return named[cfg.policy]


def partition_sequence(x: jnp.ndarray, axes=("sequence", "tensor")):
    """``partition_activations`` analog: constrain a block input's sequence dim
    over the given mesh axes so every saved copy lives 1/P per device
    (reference slices dim 0 per MP rank, ``checkpointing.py:375``). No-op
    off-mesh or for <2-D values."""
    from jax.sharding import NamedSharding, PartitionSpec

    from deepspeed_tpu.comm.mesh import get_global_mesh

    mesh = get_global_mesh()
    if mesh is None or x.ndim < 2:
        return x
    live = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    if not live:
        return x
    spec = [None] * x.ndim
    spec[1] = live if len(live) > 1 else live[0]
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec)))


def checkpoint(fn: Callable,
               config: Optional[ActivationCheckpointingConfig] = None,
               static_argnums=()) -> Callable:
    """Functional API parity with reference ``checkpoint(function, *args)``
    (``checkpointing.py:946``): returns ``fn`` wrapped to recompute its
    interior in backward under the configured policy."""
    cfg = config or ActivationCheckpointingConfig()
    inner = jax.checkpoint(fn, policy=resolve_policy(cfg),
                           static_argnums=static_argnums)
    if not cfg.partition_activations:
        return inner

    def wrapped(*args, **kwargs):
        args = tuple(partition_sequence(a) if isinstance(a, jax.Array) else a
                     for a in args)
        return inner(*args, **kwargs)

    return wrapped


def checkpoint_name(x, name: str):
    """Tag a value for named save/offload policies (the explicit analog of the
    reference's 'everything handed to CheckpointFunction is saved')."""
    from jax.ad_checkpoint import checkpoint_name as _name
    return _name(x, name)


def checkpoint_wrapper(module_cls, config: ActivationCheckpointingConfig,
                       **remat_kwargs):
    """Lifted-module variant for flax: ``nn.remat`` with the configured policy
    (what model configs' ``remat=True`` uses under the hood)."""
    import flax.linen as nn
    return nn.remat(module_cls, policy=resolve_policy(config), **remat_kwargs)
