"""Progressive Layer Dropping (PLD).

Reference analog: ``deepspeed/runtime/progressive_layer_drop.py`` — the theta
schedule ``theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar`` (paper:
arxiv 2010.13369), updated by the engine each global step and handed to the
model, which drops transformer layers stochastically with depth-scaled keep
probabilities.
"""

import math
from typing import Dict

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self) -> Dict:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> None:
        # reference _prob: (1 - p) * exp(-gamma * x) + p
        self.current_theta = (1.0 - self.theta) * \
            math.exp(-self.gamma * global_step) + self.theta


def layer_survival_probs(theta: float, num_layers: int):
    """Depth-scaled keep probabilities (PLD paper eq. 5): layer i survives
    with probability 1 - i/L * (1 - theta) — shallow layers almost always
    kept, deepest layer kept with probability theta."""
    import numpy as np
    i = np.arange(num_layers, dtype=np.float32)
    return 1.0 - i / max(num_layers - 1, 1) * (1.0 - theta)


def maybe_drop_layer(rng, x, layer_out, keep_prob):
    """Stochastic identity-skip for one layer (jit-friendly): with probability
    ``1 - keep_prob`` the layer's contribution is dropped; the kept output is
    scaled by 1/keep_prob so expectations match (inverted-dropout convention,
    as in the PLD paper's PreLN formulation)."""
    keep = jax.random.bernoulli(rng, keep_prob)
    scale = 1.0 / jnp.maximum(keep_prob, 1e-6)
    return jnp.where(keep, x + (layer_out - x) * scale, x)
