"""The training engine.

Reference analog: ``DeepSpeedEngine`` (``deepspeed/runtime/engine.py:182``) — the object
returned by ``initialize()`` that owns distributed setup, precision, partitioning,
optimizer, step loop, and checkpointing.

TPU-native redesign (SURVEY.md §7): instead of wrapping an eager module with hooks, the
engine compiles **one fused train step** — forward + backward + (at the gradient
accumulation boundary) optimizer update — under ``jax.jit`` with explicit
``NamedSharding``s implementing the configured ZeRO stage over the mesh's ``fsdp``
axis. Gradient accumulation over microbatches is a ``lax.scan`` inside the same
compiled step, so XLA overlaps the grad reduce-scatter of microbatch *i* with the
compute of *i+1* (the hand-written IPG-bucket overlap of ``stage_1_and_2.py:898``
comes out of the compiler for free).

The reference's ``forward``/``backward``/``step`` three-call protocol is kept as a
compatibility shim: ``forward`` runs a jitted value-and-grad and caches the grads,
``backward`` accumulates them into a device-resident buffer, ``step`` applies the
update at the accumulation boundary — the idiomatic entry point is ``train_batch``.
"""

import collections
import functools
import os
import time
import weakref
from typing import Any, Callable, Dict, Iterator, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.comm.comms_logging import get_comms_logger
from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.ops.optimizers import build_optimizer
from deepspeed_tpu.runtime import precision
from deepspeed_tpu.runtime.lr_schedules import build_schedule, constant_lr
from deepspeed_tpu.runtime.zero.partition import (
    build_opt_state_shardings,
    build_param_shardings,
    build_secondary_shardings,
)
from deepspeed_tpu.telemetry.compiles import watch_jit
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.runtime.dataloader import PrefetchLoader, StagedBatch
from deepspeed_tpu.runtime.sched import DispatchRing, StagedPrefetcher
from deepspeed_tpu.utils.timer import (
    BACKWARD_GLOBAL_TIMER,
    FORWARD_GLOBAL_TIMER,
    STEP_GLOBAL_TIMER,
    TRAIN_BATCH_DISPATCH_TIMER,
    TRAIN_BATCH_TIMER,
    SynchronizedWallClockTimer,
    ThroughputTimer,
)

import optax


class EngineState(NamedTuple):
    """The jit-carried training state: the analog of the engine's module params +
    optimizer internals + loss scaler, as one donated pytree."""
    step: jnp.ndarray                       # global optimizer step (int32)
    params: Any                             # fp32 master params (ZeRO-sharded)
    opt_state: Any                          # optax state (ZeRO-sharded)
    loss_scale: precision.LossScaleState
    skipped_steps: jnp.ndarray              # overflow-skipped step count


class StepOutput(NamedTuple):
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    lr: jnp.ndarray
    overflow: jnp.ndarray


def _as_apply_fn(model) -> Callable:
    """Accept a flax Module (init/apply), or a bare callable
    ``apply_fn(params, batch, rng) -> loss | (loss, aux)``."""
    if hasattr(model, "apply") and callable(model.apply):
        def apply_fn(params, batch, rng):
            kwargs = {}
            if rng is not None:
                kwargs["rngs"] = {"dropout": rng}
            return model.apply({"params": params}, batch, **kwargs)
        return apply_fn
    if callable(model):
        return model
    raise TypeError(f"model must be a flax Module or callable, got {type(model)}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _qwz_regather(leaf, sec_sharding, scale_sharding):
    """ZeRO++ qwZ re-layout: symmetric per-row int8 quantize, constrain the int8
    codes + fp32 scales to the secondary (inner-group) sharding — so the
    cross-``fsdp_out`` gather moves ~¼ the bytes of the compute dtype — then
    dequantize (reference: quantized-weights allgather, CUDAQuantizer
    partition_parameters.py:761). custom_vjp gives the straight-through
    gradient (identity) without materializing a full-precision gather of the
    original leaf on the forward path."""
    absmax = jnp.max(jnp.abs(leaf.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(leaf.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    q = jax.lax.with_sharding_constraint(q, sec_sharding)
    scale = jax.lax.with_sharding_constraint(scale, scale_sharding)
    return (q.astype(jnp.float32) * scale).astype(leaf.dtype)


def _qwz_fwd(leaf, sec_sharding, scale_sharding):
    return _qwz_regather(leaf, sec_sharding, scale_sharding), None


def _qwz_bwd(sec_sharding, scale_sharding, _, g):
    return (g,)


_qwz_regather.defvjp(_qwz_fwd, _qwz_bwd)


class DeepSpeedTPUEngine:
    def __init__(self,
                 model,
                 config: DeepSpeedTPUConfig,
                 params: Optional[Any] = None,
                 loss_fn: Optional[Callable] = None,
                 mesh: Optional[Mesh] = None,
                 example_batch: Optional[Any] = None,
                 tensor_rules: Optional[Callable] = None,
                 batch_spec: Optional[Any] = None,
                 seed: int = 0,
                 lr_scheduler: Optional[Callable] = None,
                 client_optimizer: Optional[Any] = None):
        self.config = config
        self.model = model
        self.loss_fn = loss_fn
        self.accelerator = get_accelerator()
        if config.debug_nans:
            if config.fp16.enabled:
                log_dist("debug_nans ignored with fp16: transient overflows "
                         "are expected and handled by the loss scaler",
                         ranks=[0])
            else:
                # NOTE: jax_debug_nans is process-global by construction
                jax.config.update("jax_debug_nans", True)
                log_dist("debug_nans: aborting at the first NaN-producing op "
                         "(process-global jax flag)", ranks=[0])
        elif config.fp16.enabled and jax.config.jax_debug_nans:
            # another engine in this process owns the global flag — don't
            # silently revoke its NaN protection; fp16 loss scaling here WILL
            # trip it on expected transient overflows, so the user must pick one
            log_dist("WARNING: jax_debug_nans is enabled process-globally by "
                     "another engine; this fp16 engine's overflow-skip "
                     "produces transient inf/NaN that will abort under it. "
                     "Disable debug_nans or fp16.", ranks=[0])

        # --- hierarchical ZeRO world (MiCS / ZeRO++ hpZ) ---------------------
        # Both split the ZeRO world into (fsdp_out x fsdp): MiCS shards within
        # the inner group and replicates across groups (mics.py:64); hpZ keeps
        # the full shard for memory but constrains the compute copy to the
        # inner group (partition_parameters.py:1664).
        zc = config.zero_config
        self._mics = zc.mics_shard_size is not None and zc.mics_shard_size > 0
        self._hpz = int(zc.zero_hpz_partition_size or 1)
        if self._mics and self._hpz > 1:
            raise ValueError(
                "mics_shard_size and zero_hpz_partition_size are mutually "
                "exclusive: MiCS already replicates across shard groups, so an "
                "hpZ secondary shard would be a no-op")
        inner = zc.mics_shard_size if self._mics else (self._hpz if self._hpz > 1 else 0)
        if inner and mesh is None:
            if config.mesh.fsdp == -1:
                raise ValueError("MiCS/hpZ needs an explicit mesh.fsdp size to split")
            if config.mesh.fsdp_outer == 1 and config.mesh.fsdp > inner:
                if config.mesh.fsdp % inner:
                    raise ValueError(
                        f"fsdp={config.mesh.fsdp} not divisible by shard group {inner}")
                config.mesh.fsdp_outer = config.mesh.fsdp // inner
                config.mesh.fsdp = inner
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh(config.mesh)
        mesh_lib.set_global_mesh(self.mesh)
        if inner and self.mesh.shape.get("fsdp", 1) != inner \
                and self.mesh.shape.get("fsdp_out", 1) == 1:
            log_dist(f"MiCS/hpZ shard group {inner} != mesh fsdp "
                     f"{self.mesh.shape['fsdp']}; using mesh layout as-is", ranks=[0])

        self.dp_world_size = mesh_lib.get_data_parallel_world_size(self.mesh)
        config.resolve_batch_sizes(self.dp_world_size)
        self.train_batch_size = config.train_batch_size
        self.micro_batch_size = config.train_micro_batch_size_per_gpu
        self.gradient_accumulation_steps = config.gradient_accumulation_steps
        log_dist(f"engine: {config!r} mesh={dict(self.mesh.shape)}", ranks=[0])

        if config.comms_logger.enabled:
            get_comms_logger().configure(enabled=True,
                                         verbose=config.comms_logger.verbose,
                                         prof_all=config.comms_logger.prof_all,
                                         prof_ops=config.comms_logger.prof_ops)

        self.compute_dtype = config.precision_dtype
        self.zero_stage = config.zero_config.stage
        self._apply_fn = _as_apply_fn(model)
        self._rng = jax.random.PRNGKey(seed)

        # --- LR schedule -----------------------------------------------------
        if lr_scheduler is not None:
            self.lr_schedule = lr_scheduler
        elif config.scheduler and config.scheduler.type:
            sched_params = dict(config.scheduler.params)
            self.lr_schedule = build_schedule(config.scheduler.type, sched_params)
        else:
            base_lr = (config.optimizer.params.get("lr", 1e-3)
                       if config.optimizer else 1e-3)
            self.lr_schedule = constant_lr(lr=base_lr)

        # --- optimizer -------------------------------------------------------
        # A client optimizer (optax GradientTransformation) is authoritative, as in
        # the reference (engine._configure_optimizer prefers the client optimizer).
        if client_optimizer is not None:
            if not (hasattr(client_optimizer, "init") and hasattr(client_optimizer, "update")):
                raise TypeError("client optimizer must be an optax GradientTransformation "
                                f"(has init/update), got {type(client_optimizer)}")
            self.tx = client_optimizer
        else:
            opt_type = config.optimizer.type if config.optimizer else "adamw"
            opt_params = dict(config.optimizer.params) if config.optimizer else {}
            self.tx = build_optimizer(opt_type, opt_params, lr_schedule=self.lr_schedule)

        # batch sharding: leading dim over (data, fsdp) unless caller overrides
        self.batch_spec = batch_spec if batch_spec is not None \
            else PartitionSpec(mesh_lib.batch_axes(self.mesh))
        self.batch_sharding = NamedSharding(self.mesh, self.batch_spec)

        # --- ZeRO-Infinity parameter offload ---------------------------------
        # Params live on host/NVMe and stream through HBM layer-group by
        # layer-group (runtime/param_offload.py; reference
        # partitioned_param_swapper.py:37). A non-"none" offload_param either
        # takes effect here or RAISES — never parses-and-ignores.
        self._param_offload = None
        _pcfg = config.zero_config.offload_param
        if _pcfg.device != "none":
            from deepspeed_tpu.runtime.param_offload import (
                ParamOffloadTrainer, validate_param_offload)
            # fail fast BEFORE host param init (which may allocate tens of GB)
            validate_param_offload(config, model)
            if client_optimizer is not None:
                raise ValueError(
                    "offload_param requires a config-typed optimizer (the "
                    "update runs in the fused host kernel, not optax)")
            if params is None:
                if example_batch is None:
                    raise ValueError("example_batch required to init a flax "
                                     "Module")
                self._rng, init_rng = jax.random.split(self._rng)
                params = self._host_init_params(model, example_batch, init_rng)
            params = jax.tree.map(lambda x: np.asarray(x), params)
            scalar_sharding = NamedSharding(self.mesh, PartitionSpec())
            self.param_shardings = None
            self.opt_state_shardings = ()
            self.state = EngineState(
                step=jax.device_put(jnp.int32(0), scalar_sharding),
                params=(),
                opt_state=(),
                loss_scale=jax.device_put(
                    precision.init_loss_scale(config.fp16), scalar_sharding),
                skipped_steps=jax.device_put(jnp.int32(0), scalar_sharding),
            )
            self.state_shardings = None
            self._param_offload = ParamOffloadTrainer(
                model, config, params, self.mesh, self.batch_sharding,
                self.lr_schedule, tensor_rules=tensor_rules)
            params = None      # host copy now owned by the trainer's masters
            # checkpoint interop: host masters are the authoritative weights
            self._offload = self._param_offload.opt
            self._offload_grad_fn = None
            self._offload_apply_fn = None
            self._params_treedef = self._param_offload.treedef

        # --- parameter init + sharding --------------------------------------
        if self._param_offload is not None:
            pass
        elif params is None:
            if not hasattr(model, "init"):
                raise ValueError("pass `params` or a flax Module with .init")
            if example_batch is None:
                raise ValueError("example_batch required to init a flax Module")
            self._rng, init_rng = jax.random.split(self._rng)
            variables = jax.eval_shape(lambda r: model.init(r, example_batch), init_rng)
            params_shape = variables["params"]
            self.param_shardings = build_param_shardings(
                params_shape, self.mesh, self.zero_stage, tensor_rules,
                mics=self._mics)

            def _init(r):
                return model.init(r, example_batch)["params"]
            params = jax.jit(_init, out_shardings=self.param_shardings)(init_rng)
        else:
            self.param_shardings = build_param_shardings(
                params, self.mesh, self.zero_stage, tensor_rules,
                mics=self._mics)
            params = jax.device_put(
                jax.tree.map(lambda x: np.asarray(x), params), self.param_shardings)

        if self._param_offload is None:
            # fp32 master weights (reference: FP16_Optimizer / BF16_Optimizer)
            params = jax.tree.map(
                lambda x: x.astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)

            # --- optimizer-state offload tier (ZeRO-Offload / Infinity) ------
            # Constructed BEFORE device state: under offload the device holds
            # only compute-dtype param shadows — no fp32 masters, no optimizer
            # moments in HBM (that is the point of the tier; reference keeps
            # fp16 shards on device and fp32 masters + moments on host).
            self._offload = None
            self._offload_grad_fn = None
            self._offload_apply_fn = None
            offload_cfg = config.zero_config.offload_optimizer
            if offload_cfg.device in ("cpu", "nvme"):
                from deepspeed_tpu.runtime.offload import HostOffloadOptimizer
                host_leaves = [np.asarray(jax.device_get(p), np.float32)
                               for p in jax.tree.leaves(params)]
                opt_type = config.optimizer.type if config.optimizer else "adamw"
                self._offload = HostOffloadOptimizer(
                    host_leaves, opt_type,
                    dict(config.optimizer.params) if config.optimizer else {},
                    offload_cfg)
                self._params_treedef = jax.tree_util.tree_structure(params)
                params = jax.jit(
                    lambda p: precision.cast_to_compute(p, self.compute_dtype),
                    out_shardings=self.param_shardings)(params)
                self.opt_state_shardings = ()
                opt_state = ()
            else:
                param_specs = jax.tree.map(
                    lambda s: s.spec, self.param_shardings,
                    is_leaf=lambda x: isinstance(x, NamedSharding))
                opt_state_shape = jax.eval_shape(self.tx.init, params)
                self.opt_state_shardings = build_opt_state_shardings(
                    opt_state_shape, params, param_specs, self.mesh,
                    max(self.zero_stage, 0), mics=self._mics)
                opt_state = jax.jit(self.tx.init,
                                    out_shardings=self.opt_state_shardings)(params)

            scalar_sharding = NamedSharding(self.mesh, PartitionSpec())
            self.state = EngineState(
                step=jax.device_put(jnp.int32(0), scalar_sharding),
                params=params,
                opt_state=opt_state,
                loss_scale=jax.device_put(precision.init_loss_scale(config.fp16),
                                          scalar_sharding),
                skipped_steps=jax.device_put(jnp.int32(0), scalar_sharding),
            )
            self.state_shardings = EngineState(
                step=scalar_sharding,
                params=self.param_shardings,
                opt_state=self.opt_state_shardings,
                loss_scale=jax.tree.map(lambda _: scalar_sharding,
                                        self.state.loss_scale),
                skipped_steps=scalar_sharding,
            )

        # hpZ secondary compute-copy shardings (stage 3 only; with the hpZ split
        # active, compute params are constrained to the inner fsdp sub-axis so
        # per-layer allgathers stay within the shard group)
        self._secondary_shardings = None
        if (self._hpz > 1 and self.zero_stage >= 3
                and self.mesh.shape.get("fsdp_out", 1) > 1):
            self._secondary_shardings = build_secondary_shardings(
                self.param_shardings, self.mesh)
        self._quantized_weights = bool(zc.zero_quantized_weights)
        if self._quantized_weights and self._secondary_shardings is None:
            log_dist("zero_quantized_weights (qwZ) takes effect on the hpZ "
                     "secondary gather; set zero_hpz_partition_size > 1 — ignored",
                     ranks=[0])
            self._quantized_weights = False
        # qgZ: quantized gradient reduction (reference all_to_all_quant_reduce,
        # runtime/comm/coalesced_collectives.py:31 + csrc/quantization/
        # quant_reduce.cu). When the mesh has replica batch axes (axes that
        # shard the batch but no parameter — the pure-DP all-reduce hops), the
        # gradient phase runs in a partial-manual shard_map and the reduction
        # over those axes moves REAL int8 bytes on the wire
        # (runtime/zero/qgz.py). Without replica axes (pure-fsdp ZeRO-3) the
        # reduction is fused into XLA's backward and the flag falls back to
        # the int8 round-trip numerics simulation in _grads_one_micro.
        self._quantized_gradients = bool(zc.zero_quantized_gradients)
        # replica (pure-DP) batch axes — shared by every wire-compression
        # feature that opens the partial-manual gradient phase (qgZ int8,
        # sparse embedding grads)
        from deepspeed_tpu.runtime.zero.qgz import replica_grad_axes
        self._replica_axes = replica_grad_axes(
            self.mesh, self.batch_spec, self.param_shardings) \
            if self._param_offload is None else ()
        self._qgz_axes = ()
        if self._quantized_gradients:
            self._qgz_axes = self._replica_axes
            if self._qgz_axes:
                log_dist("qgZ: int8-wire gradient reduction over replica "
                         f"axes {self._qgz_axes} (hierarchical quantized "
                         "reduce-scatter + regather)", ranks=[0])
            else:
                import warnings
                msg = ("zero_quantized_gradients=true but the mesh has NO "
                       "replica batch axis (pure-fsdp ZeRO-3): there is no "
                       "pure-DP all-reduce hop to compress, so NO bytes are "
                       "saved on the wire. Gradients still pay the int8 "
                       "round-trip quantization noise (reference-fidelity "
                       "numerics). Either add a replica axis (a 'data' mesh "
                       "axis, or split fsdp via mics_shard_size < world so "
                       "'fsdp_out' replicates) or disable "
                       "zero_quantized_gradients. See "
                       "docs/parallelism.md#qgz.")
                warnings.warn("qgZ: " + msg, UserWarning, stacklevel=3)
                logger.warning("qgZ: %s", msg)

        # --- resilience step guard -------------------------------------------
        # When armed, _update treats non-finite grads as an overflow in EVERY
        # precision mode (bf16/fp32 included): update dropped, params kept,
        # skipped_steps incremented. Armed from an explicit "resilience"
        # config group or at runtime via set_nonfinite_guard (the
        # FaultTolerantRunner's step-guard hook).
        rcfg = getattr(config, "resilience", None)
        self._guard_nonfinite = bool(
            getattr(config, "resilience_explicit", False) and rcfg is not None
            and rcfg.step_guard.enabled and rcfg.step_guard.policy == "skip")

        # --- compiled functions ----------------------------------------------
        self._reset_compiled_fns()

        # --- compat-shim bookkeeping ----------------------------------------
        self._grad_buffer = None
        self._accum_count = 0
        self._pending = None            # cached (loss, grads) from forward()

        # progressive layer drop (reference: engine.py:346 _configure_pld +
        # :1871 per-step update_state)
        self.progressive_layer_drop = None
        if config.pld.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                ProgressiveLayerDrop)
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.pld.theta, gamma=config.pld.gamma)
        # eigenvalue (reference: engine.py eigenvalue_enabled + compression MoQ)
        self.eigenvalue = None
        self.block_eigenvalues = None
        if config.eigenvalue.enabled:
            from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
            self.eigenvalue = Eigenvalue(config.eigenvalue)
        # sparse gradients (reference engine.py:2518 sparse_allreduce_bucket):
        # embedding-like leaves reduce over the replica axes with the sparse
        # (indices, values) wire format inside the partial-manual gradient
        # phase — same seam as qgZ; the two compose (sparse leaves go sparse,
        # the rest int8 when qgZ is also on)
        self.sparse_gradients_enabled = config.sparse_gradients_enabled
        self._sparse_grad_axes = ()
        self._sparse_grad_paths = ()
        if self.sparse_gradients_enabled:
            from deepspeed_tpu.utils.tree import tree_path_str
            # tied-embedding models get a DENSE head gradient over the whole
            # vocab — top-k truncation would silently drop real mass, so the
            # model's tie flag disables the path outright
            mcfg = getattr(model, "cfg", None)
            tied = bool(getattr(mcfg, "tie_embeddings", False) or
                        getattr(mcfg, "tie_word_embeddings", False))
            axes = self._replica_axes
            paths = () if tied else tuple(
                tree_path_str(p)
                for p, leaf in jax.tree_util.tree_flatten_with_path(
                    self.state.params)[0]
                if hasattr(leaf, "ndim") and leaf.ndim == 2
                and leaf.shape[0] >= 512
                and "embed" in tree_path_str(p).lower())
            if axes and paths:
                self._sparse_grad_axes = axes
                self._sparse_grad_paths = paths
                log_dist(
                    f"sparse_gradients: sparse wire reduction over {axes} "
                    f"for {len(paths)} embedding leaves (top-k rows = batch "
                    "tokens — exact for lookup-only embedding grads)",
                    ranks=[0])
            else:
                log_dist(
                    "sparse_gradients: "
                    + ("model ties its embeddings (dense head grads) — "
                       if tied else
                       "no replica batch axis or no embedding-like leaf — ")
                    + "gradients reduce densely", ranks=[0])

        # --- comm compression (comm/compress.py) ------------------------------
        # Quantized error-feedback collectives + bucketed backward/
        # reduce-scatter overlap over the replica axes. Default OFF =
        # today's exact semantics. When active it OWNS the wire: qgZ
        # (zero_quantized_gradients) defers to it — one compression layer,
        # one error-feedback state, one set of wire-byte counters.
        ccfg = config.comm_compression
        self._comm_compress = None
        self._overlap_meta: List[Dict[str, Any]] = []
        self._overlap_wire_total = 0
        if ccfg.enabled:
            if self._param_offload is not None or self._offload is not None:
                log_dist("comm_compression: disabled — offload tiers run a "
                         "host-synchronous optimizer step whose reductions "
                         "keep today's wire format", ranks=[0])
            elif not self._replica_axes:
                import warnings
                msg = ("comm_compression enabled but the mesh has NO "
                       "replica batch axis (pure-fsdp ZeRO-3): there is no "
                       "pure-DP all-reduce hop to compress, so NO bytes "
                       "are saved on the wire — the group is ignored. Add "
                       "a replica axis (a 'data' mesh axis, or split fsdp "
                       "via mics_shard_size < world so 'fsdp_out' "
                       "replicates). See docs/performance.md#wire-"
                       "compression--overlap.")
                warnings.warn(msg, UserWarning, stacklevel=3)
                logger.warning(msg)
            else:
                from deepspeed_tpu.comm.compress import (CommCompressState,
                                                         GradCompressor,
                                                         with_error_feedback)
                comp = GradCompressor(ccfg, self._replica_axes, self.mesh)
                comp.build(self.state.params,
                           itemsize=jnp.dtype(config.grad_accum_dtype)
                           .itemsize,
                           exclude_paths=self._sparse_grad_paths)
                if not comp.buckets:
                    log_dist("comm_compression: no leaf meets min_size "
                             f"({ccfg.min_size}) — nothing to compress",
                             ranks=[0])
                else:
                    self._comm_compress = comp
                    # overlap spans describe the per-bucket schedule; a
                    # fused single bucket (overlap=False) has no schedule
                    # to claim, so nothing rides the comm-overlap track
                    self._overlap_meta = comp.bucket_summaries() \
                        if ccfg.overlap else []
                    self._overlap_wire_total = max(
                        sum(b["wire_bytes"] for b in self._overlap_meta), 1)
                    if self._quantized_gradients:
                        log_dist("comm_compression supersedes "
                                 "zero_quantized_gradients on the replica "
                                 "axes (one compression layer owns the "
                                 "wire)", ranks=[0])
                        self._qgz_axes = ()
                        # clearing the axes alone would re-arm the
                        # per-microbatch int8 round-trip fallback in
                        # _grads_one_micro — the wire is quantized ONCE,
                        # by the bucketed reduction
                        self._quantized_gradients = False
                    # error-feedback residuals ride the optimizer state so
                    # they checkpoint and survive the mesh-portable resume
                    ef_shardings = comp.error_feedback_shardings(self.mesh)
                    ef = jax.jit(comp.zero_error_feedback,
                                 out_shardings=ef_shardings)() \
                        if comp.ef_enabled() else ()
                    self.tx = with_error_feedback(self.tx,
                                                  comp.zero_error_feedback)
                    self.state = self.state._replace(
                        opt_state=CommCompressState(
                            inner=self.state.opt_state, error_feedback=ef))
                    self.opt_state_shardings = CommCompressState(
                        inner=self.opt_state_shardings,
                        error_feedback=ef_shardings)
                    self.state_shardings = self.state_shardings._replace(
                        opt_state=self.opt_state_shardings)
                    log_dist(
                        f"comm_compression: {len(comp.buckets)} bucket(s) "
                        f"over {self._replica_axes} "
                        f"(wire={ccfg.wire_dtype}, chunk={ccfg.chunk}, "
                        f"error_feedback={'on' if comp.ef_enabled() else 'off'}, "
                        f"overlap={'per-bucket' if ccfg.overlap else 'fused'})",
                        ranks=[0])

        # --- async step pipeline (deferred metric readback + prefetch) --------
        # config.async_pipeline; disabled -> per-step readback semantics are
        # bit-for-bit today's (no ring, no extra sync, device-array metrics)
        acfg = config.async_pipeline
        self._async_enabled = bool(acfg.enabled)
        if self._async_enabled and (self._param_offload is not None
                                    or self._offload is not None):
            # the fused host-optimizer step is host-synchronous by
            # construction — a deferred ring would never fill and async-mode
            # consumers (the resilience runner) would go blind
            log_dist("async_pipeline: disabled — offload tiers run a "
                     "host-synchronous optimizer step (nothing to defer)",
                     ranks=[0])
            self._async_enabled = False
        # the configured cadence survives enable/disable toggles; the live
        # _sync_every collapses to 1 whenever the pipeline is off
        self._sync_every_cfg = int(acfg.sync_every)
        # the shared host-orchestration core (runtime/sched.py): DispatchRing
        # owns the device-side pending ring, the bounded drained-entry queue
        # and the window anchor; StagedPrefetcher owns the identity-keyed
        # loader lifecycle. The serve loop consumes the same classes —
        # engine-specific host fan-out stays in _drain_metric_ring.
        self._sched = DispatchRing(capacity=4096)
        self._staged = StagedPrefetcher()
        self._sync_every = self._sync_every_cfg if self._async_enabled else 1
        self._prefetch_enabled = self._async_enabled and bool(acfg.prefetch)
        if self._prefetch_enabled and (config.flops_profiler.enabled
                                       or config.eigenvalue.enabled):
            # both side paths materialize the batch on host (np.asarray),
            # which a staged multi-host array cannot satisfy — profiling /
            # diagnostic runs keep inline staging
            log_dist("async_pipeline: prefetch disabled — flops_profiler/"
                     "eigenvalue need host-materialized batches", ranks=[0])
            self._prefetch_enabled = False
        self._prefetch_depth = int(acfg.prefetch_depth)
        if self._async_enabled and config.wall_clock_breakdown:
            log_dist("async_pipeline: wall_clock_breakdown forces a device "
                     "sync per timer start/stop — the breakdown timers will "
                     "serialize the pipeline they are measuring", ranks=[0])

        # --- bookkeeping / observability -------------------------------------
        self.tracer = get_tracer()     # dstrace span tracer (DSTPU_TRACE)
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.timers = SynchronizedWallClockTimer(
            synchronize=config.wall_clock_breakdown)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size,
            steps_per_output=config.steps_per_print,
            synchronize=not self._async_enabled)
        self._last_metrics: Dict[str, float] = {}
        self.monitor = None
        if (config.tensorboard.enabled or config.csv_monitor.enabled
                or config.wandb.enabled):
            from deepspeed_tpu.monitor.monitor import MonitorMaster
            self.monitor = MonitorMaster(config)
            if self.monitor.enabled:
                # tracer instant-events (guard trips, chaos injections, ...)
                # fan out to the monitor's `events` sink alongside gauges.
                # Bound through a weakref: the process-global tracer outlives
                # any engine, and a strong bound method would pin a torn-down
                # engine's monitor (open TB/CSV handles) for the process
                # lifetime and keep routing events to its stale writers.
                mon_ref = weakref.ref(self.monitor)

                def _events_sink(name, step):
                    mon = mon_ref()
                    if mon is not None:
                        mon.write_instant(name, step)

                self.tracer.attach_sink(_events_sink)

        # --- data efficiency (curriculum learning + random-LTD) --------------
        # reference: engine.py curriculum hooks + runtime/data_pipeline/
        self.curriculum_scheduler = None
        self.random_ltd_scheduler = None
        if config.curriculum_learning_legacy.enabled:
            from deepspeed_tpu.data_pipeline import CurriculumScheduler
            c = config.curriculum_learning_legacy
            self.curriculum_scheduler = CurriculumScheduler({
                "schedule_type": c.schedule_type,
                "min_difficulty": c.min_difficulty,
                "max_difficulty": c.max_difficulty,
                "schedule_config": c.schedule_config})
        # per-metric curriculum sampling lives in CurriculumDataSampler (which owns
        # its schedulers); the engine only drives the legacy seqlen curriculum + LTD
        if config.data_efficiency.random_ltd_enabled:
            from deepspeed_tpu.data_pipeline import RandomLTDScheduler
            ltd = dict(config.data_efficiency.random_ltd)
            ltd.setdefault("global_batch_size", self.train_batch_size)
            self.random_ltd_scheduler = RandomLTDScheduler(ltd)

        # --- compression (QAT / pruning; reference deepspeed/compression) -----
        self.compressor = None
        self._compression_key = None
        if config.compression_config:
            from deepspeed_tpu.compression import init_compression
            self.compressor = init_compression(
                self.state.params,
                {"compression_training": config.compression_config})
            self.compressor.maybe_freeze_masks(self.state.params)
            self._compression_key = self.compressor.schedule_key()

        # --- dsmem: memory observability + analytic preflight ------------------
        # the sampler rides every traced run for free (HBM/RSS counter
        # tracks in the DSTPU_TRACE dump); the "memory" config group adds
        # the analytic preflight and the background cadence thread
        self._mem_sampler = None
        self.last_oom: Optional[Dict[str, Any]] = None
        if config.memory.enabled or self.tracer.enabled:
            from deepspeed_tpu.telemetry.memory import MemorySampler
            self._mem_sampler = MemorySampler(tracer=self.tracer,
                                              window=config.memory.window)
            if config.memory.enabled and config.memory.cadence_s > 0:
                self._mem_sampler.start(config.memory.cadence_s)
        if config.memory.enabled and config.memory.preflight != "off":
            self._memory_preflight(config.memory.preflight)
        if self._mem_sampler is not None:
            # the init watermark: params + optimizer state are resident now
            self._mem_sampler.sample(step=0, phase="init")

    @staticmethod
    def _host_init_params(model, example_batch, init_rng):
        """Initialize params in HOST memory (CPU backend): under offload_param
        the model may not fit device HBM, so device-side init is not an
        option. Falls back to default-device init + fetch when no CPU backend
        exists (then the model must fit HBM once; pass ``params`` to avoid)."""
        if not hasattr(model, "init"):
            raise ValueError("pass `params` or a flax Module with .init")

        def _init(r):
            return model.init(r, example_batch)["params"]
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            log_dist("offload_param: no CPU backend for host init — "
                     "initializing on the default device (model must fit HBM "
                     "once; pass `params` for weights-bigger-than-HBM runs)",
                     ranks=[0])
            return jax.device_get(jax.jit(_init)(init_rng))
        with jax.default_device(cpu):
            return jax.device_get(jax.jit(_init)(jax.device_put(init_rng, cpu)))

    def _reset_compiled_fns(self):
        """Drop every cached compiled step fn. The single authority for the set of
        jitted-fn caches — used at init and whenever static trace structure
        changes (e.g. a compression-schedule transition)."""
        if not hasattr(self, "training"):
            # API-parity mode flags are set once: a cache reset (compression
            # transition, checkpoint load) must not undo a user's eval() /
            # compile() calls (round-2 advisor finding).
            self.training = True        # module-mode parity (train()/eval())
            self._compiled = False      # engine.compile() parity flag
        self._train_batch_fn = None     # gas microbatches fused via scan
        self._micro_fwd_bwd_fn = None   # compat path: per-microbatch grads
        self._apply_update_fn = None    # compat path: update at boundary
        self._eval_fn = None
        self._offload_grad_fn = None
        self._offload_apply_fn = None

    # ------------------------------------------------------------------
    # loss computation
    # ------------------------------------------------------------------
    def _hpz_constrain(self, compute_params):
        """ZeRO++ hpZ: re-lay the compute copy onto the secondary (inner-group)
        sharding — one cross-group gather here, node-local gathers per layer.
        With qwZ the cross-group hop moves int8 + per-row scales instead of the
        compute dtype (reference: quantized-weights allgather, CUDAQuantizer
        partition_parameters.py:761)."""
        if not self._quantized_weights:
            return jax.lax.with_sharding_constraint(
                compute_params, self._secondary_shardings)

        def requantize(leaf, primary, sharding):
            # only quantize leaves whose layout actually changes across the
            # fsdp_out hop — replicated / tensor-only leaves have no cross-group
            # gather to cheapen, so int8 noise there is pure loss
            if (leaf.ndim < 2 or not jnp.issubdtype(leaf.dtype, jnp.floating)
                    or primary.spec == sharding.spec):
                return jax.lax.with_sharding_constraint(leaf, sharding)
            s_spec = PartitionSpec(*(list(sharding.spec)[:leaf.ndim - 1] + [None])) \
                if len(sharding.spec) else PartitionSpec()
            return _qwz_regather(leaf, sharding,
                                 NamedSharding(self.mesh, s_spec))

        return jax.tree.map(requantize, compute_params, self.param_shardings,
                            self._secondary_shardings)

    def _compute_loss(self, params, batch, rng):
        compute_params = precision.cast_to_compute(params, self.compute_dtype)
        if self._secondary_shardings is not None:
            compute_params = self._hpz_constrain(compute_params)
        if self.compressor is not None:
            # fake-quant + pruning masks with straight-through grads, traced into
            # the step under the current host-side schedule snapshot
            compute_params = self.compressor.transform(compute_params)
        out = self._apply_fn(compute_params, batch, rng)
        if self.loss_fn is not None:
            out = self.loss_fn(out, batch)
        if isinstance(out, tuple):
            out = out[0]
        return jnp.asarray(out, jnp.float32)

    def _grads_one_micro(self, params, batch, rng, scale):
        """Value-and-grad of (scaled) loss for one microbatch. With qgZ on and
        no replica axis to carry the real int8-wire collective, every
        microbatch gradient goes through an int8 round-trip before it is
        accumulated/reduced — the fidelity contract of the reference's
        quantized-gradient collectives. With replica axes present the wire
        quantization itself supplies the numerics (runtime/zero/qgz.py)."""
        def scaled_loss(p):
            return self._compute_loss(p, batch, rng) * scale
        loss_scaled, grads = jax.value_and_grad(scaled_loss)(params)
        if self._quantized_gradients and not self._qgz_axes:
            from deepspeed_tpu.ops.pallas.quant import dequantize_int8, quantize_int8
            from deepspeed_tpu.runtime.zero.qgz import MIN_QUANT_SIZE

            def qdq(g):
                # tiny leaves (norm scales, biases) are bandwidth-irrelevant —
                # the reference buckets them with everything else, but skipping
                # them avoids int8 noise on the most sensitive parameters
                # (same threshold as the wire path, qgz.MIN_QUANT_SIZE)
                if g.ndim < 1 or g.size < MIN_QUANT_SIZE:
                    return g
                q, s = quantize_int8(g)
                return dequantize_int8(q, s, dtype=g.dtype)
            grads = jax.tree.map(qdq, grads)
        return loss_scaled / scale, grads

    # ------------------------------------------------------------------
    # fused train_batch: scan over gas microbatches + update, one jit
    # ------------------------------------------------------------------
    def _make_grads_phase(self):
        """Builds ``(params, stacked_batch [gas, ...], rngs [gas], scale) ->
        (avg loss, per-micro-summed grads in grad_accum_dtype)``. When qgZ has
        replica axes, the whole phase (fwd/bwd + gas scan) runs inside a
        partial-manual shard_map: per-device partial grads, then an int8-wire
        hierarchical reduce over the replica axes — real bandwidth compression,
        not just the reference's numerics (runtime/zero/qgz.py). fsdp/tensor
        axes stay XLA-automatic inside the region."""
        gas = self.gradient_accumulation_steps
        acc_dtype = self.config.grad_accum_dtype

        def grads_phase(params, stacked_batch, rngs, scale):
            if gas == 1:
                # no accumulation buffer at all: one microbatch, grads go
                # straight into the update (saves a full param-tree carry)
                batch = jax.tree.map(lambda x: x[0], stacked_batch)
                loss, grads = self._grads_one_micro(params, batch,
                                                    rngs[0], scale)
                return loss, jax.tree.map(lambda g: g.astype(acc_dtype), grads)

            def micro(carry, xs):
                grad_acc, loss_acc = carry
                batch, r = xs
                loss, grads = self._grads_one_micro(params, batch, r, scale)
                grad_acc = jax.tree.map(
                    lambda a, g: a + g.astype(acc_dtype), grad_acc, grads)
                return (grad_acc, loss_acc + loss), None

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, acc_dtype), params)
            (grads, loss_sum), _ = jax.lax.scan(
                micro, (zero_grads, jnp.float32(0.0)), (stacked_batch, rngs))
            return loss_sum / gas, grads

        from deepspeed_tpu.runtime.zero.qgz import wrap_grads_phase
        if self._comm_compress is not None:
            # comm_compression owns the manual-region reduction: per-bucket
            # facade-recorded quantized all-reduce with the error-feedback
            # residuals threaded through the shard_map (sparse embedding
            # leaves keep their sparse wire format via the fallback)
            comp = self._comm_compress
            axes = self._replica_axes
            sync = comp.make_sync_fn(
                fallback_leaf_sync=self._compress_fallback_sync(axes))
            if comp.ef_enabled():
                return wrap_grads_phase(grads_phase, self.mesh, axes,
                                        self.batch_spec, stacked=True,
                                        sync_fn=sync,
                                        ef_specs=comp.ef_partition_specs())

            def sync_no_ef(grads, batch):
                reduced, _ = sync(grads, batch, ())
                return reduced

            return wrap_grads_phase(grads_phase, self.mesh, axes,
                                    self.batch_spec, stacked=True,
                                    sync_fn=sync_no_ef)
        axes = self._qgz_axes or self._sparse_grad_axes
        return wrap_grads_phase(grads_phase, self.mesh, axes,
                                self.batch_spec, stacked=True,
                                sync_fn=self._make_grad_sync(axes))

    @staticmethod
    def _batch_token_count(batch) -> int:
        """k = batch tokens on this device: a pure-lookup embedding grad
        touches at most one row per token, so top-k at this k keeps every
        touched row and the sparse reduction is EXACT. Max over integer
        leaves — small int side fields (bucket ids, lengths) must not
        shrink k below the token count."""
        return max((int(leaf.size) for leaf in jax.tree.leaves(batch)
                    if jnp.issubdtype(leaf.dtype, jnp.integer)),
                   default=0)

    def _sparse_wire_policy(self, axes):
        """THE sparse-embedding wire rule, shared by the composite grad
        sync and the comm_compression fallback so the win heuristic can
        never drift between them: returns ``fn(path_str, g, k_tokens) ->
        reduced | None`` (None = not a sparse-profitable leaf — caller
        falls through to its dense policy), or None when no sparse leaves
        are configured."""
        if not self._sparse_grad_paths or not axes:
            return None
        from deepspeed_tpu.runtime.sparse_tensor import sparse_grad_sync
        sparse_paths = set(self._sparse_grad_paths)
        world = 1
        for ax in axes:
            world *= self.mesh.shape[ax]

        def leaf_rule(p, g, k_tokens):
            if p not in sparse_paths or not k_tokens:
                return None
            v, d = g.shape
            k = min(v, k_tokens)
            # wire win vs dense: the gathered sparse representation is
            # O(k·(d+1)·world) rows across the replica group, a dense
            # all-reduce O(v·d) — sparse only pays when the batch's token
            # set is small relative to V/world
            if k * (d + 1) * world < v * d:
                return sparse_grad_sync(g, axes, k)
            return None

        return leaf_rule

    def _compress_fallback_sync(self, axes):
        """Leaf sync for leaves OUTSIDE every compression bucket
        (sub-min_size, non-float, or sparse-selected): sparse embedding
        leaves keep the sparse (indices, values) wire format, everything
        else a full-precision pmean. None when no sparse leaves are
        configured (the compressor's default pmean fallback applies)."""
        sparse_rule = self._sparse_wire_policy(axes)
        if sparse_rule is None:
            return None
        from deepspeed_tpu.utils.tree import tree_path_str

        def fallback(path, g, batch):
            out = sparse_rule(tree_path_str(path), g,
                              self._batch_token_count(batch))
            return jax.lax.pmean(g, axes) if out is None else out

        return fallback

    def _make_grad_sync(self, axes):
        """Per-leaf wire policy for the manual-region gradient reduction:
        embedding leaves (sparse_gradients) use the sparse (indices, values)
        format via the shared ``_sparse_wire_policy`` rule, everything else
        int8 (qgZ) or plain fp pmean. Returns None (the default quantized
        sync) when no sparse leaves are selected."""
        sparse_rule = self._sparse_wire_policy(axes)
        if sparse_rule is None:
            return None
        from deepspeed_tpu.runtime.zero.qgz import quantized_grad_sync
        from deepspeed_tpu.utils.tree import tree_path_str
        qgz_on = bool(self._qgz_axes)

        def sync_fn(grads, batch):
            k_tokens = self._batch_token_count(batch)

            def leaf_sync(path, g):
                out = sparse_rule(tree_path_str(path), g, k_tokens)
                if out is not None:
                    return out
                if qgz_on:
                    return quantized_grad_sync(g, axes)
                return jax.lax.pmean(g, axes)

            return jax.tree_util.tree_map_with_path(leaf_sync, grads)

        return sync_fn

    def _build_train_batch_fn(self):
        cfg = self.config
        gas = self.gradient_accumulation_steps
        clip = cfg.gradient_clipping
        fp16 = cfg.fp16
        tx = self.tx
        lr_schedule = self.lr_schedule
        grads_phase = self._make_grads_phase()

        ef_active = (self._comm_compress is not None
                     and self._comm_compress.ef_enabled())

        def train_batch_step(state: EngineState, stacked_batch, rng) -> Tuple[EngineState, StepOutput]:
            scale = state.loss_scale.scale
            rngs = jax.random.split(rng, gas)
            if ef_active:
                # comm_compression error feedback: residuals ride the
                # optimizer-state wrapper into the manual region and come
                # back refreshed by the bucketed quantized reduction
                ef = state.opt_state.error_feedback
                loss, grads, new_ef = grads_phase(state.params,
                                                  stacked_batch, rngs,
                                                  scale, ef)
            else:
                loss, grads = grads_phase(state.params, stacked_batch,
                                          rngs, scale)
            # unscale + average over gas in fp32 (reference scales loss by 1/gas
            # pre-bwd; accumulation dtype may be lower via data_types config).
            # No per-microbatch overflow check is needed (the reference checks
            # per-reduction, stage3.py:1290): IEEE non-finites are absorbing
            # under addition (inf + -inf = NaN, inf + x = inf), so any
            # microbatch overflow survives into the accumulated sum and the
            # single check in _update catches it — tested in
            # test_fp16_per_microbatch_overflow_detected.
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / (scale * gas), grads)
            new_state, out = self._update(state, grads, tx, lr_schedule, clip, fp16)
            if ef_active:
                # a residual refreshed from non-finite grads would poison
                # every later step: on overflow the old residuals survive
                # with the params (exactly the keep_old contract)
                kept = jax.tree.map(
                    lambda n, o: jnp.where(out.overflow, o, n), new_ef, ef)
                new_state = new_state._replace(
                    opt_state=new_state.opt_state._replace(
                        error_feedback=kept))
            return new_state, out._replace(loss=loss)

        donate = (0,)
        # watch_jit: every XLA compile of the step fn emits an xla/compile
        # instant (qualname + shape signature + wall ms) and bumps the
        # process compile counter — benches assert ZERO compiles inside
        # their timed window after warmup (telemetry/compiles.py)
        self._train_batch_fn = watch_jit(jax.jit(
            train_batch_step,
            donate_argnums=donate,
            out_shardings=(self.state_shardings, None),
        ), "engine.train_batch_step")

    def _update(self, state: EngineState, grads, tx, lr_schedule, clip,
                fp16) -> Tuple[EngineState, StepOutput]:
        """Optimizer update with overflow skip + dynamic loss scale + clipping.
        reference: stage3.py step (:2061) / fused_optimizer.py step."""
        if fp16.enabled or self._guard_nonfinite:
            # fp16: detect overflow, neutralize non-finite grads so the (discarded)
            # update arithmetic stays clean, and skip the step (reference
            # _overflow_check_and_loss_scale_update). This single post-sum
            # check also covers per-microbatch overflow under the gas scan —
            # IEEE non-finites are absorbing under addition. The resilience
            # step guard reuses the same path for bf16/fp32 (skip, no scaler).
            overflow = precision.has_inf_or_nan(grads)
            safe_grads = jax.tree.map(
                lambda g: jnp.where(jnp.isfinite(g), g, jnp.zeros_like(g)), grads)
        else:
            # bf16/fp32 without the guard: no loss scaler in the reference
            # either — a NaN propagates into params/loss so divergence is
            # visible, never silently masked.
            overflow = jnp.bool_(False)
            safe_grads = grads
        clipped, grad_norm = precision.clip_by_global_norm(safe_grads, clip)
        updates, new_opt_state = tx.update(clipped, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        def keep_old(new, old):
            return jax.tree.map(lambda n, o: jnp.where(overflow, o, n), new, old)

        new_params = keep_old(new_params, state.params)
        new_opt_state = keep_old(new_opt_state, state.opt_state)
        new_scale_state = precision.update_loss_scale(state.loss_scale, overflow, fp16)
        lr = jnp.asarray(lr_schedule(state.step), jnp.float32)
        new_state = EngineState(
            step=state.step + jnp.where(overflow, 0, 1).astype(jnp.int32),
            params=new_params,
            opt_state=new_opt_state,
            loss_scale=new_scale_state,
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
        )
        return new_state, StepOutput(loss=jnp.float32(0.0), grad_norm=grad_norm,
                                     lr=lr, overflow=overflow)

    @staticmethod
    def stack_microbatches(data_iter, gas: int):
        """Pull ``gas`` microbatches and stack every leaf to [gas, ...] —
        THE stacked-batch contract train_batch consumes (shared with the
        resilience runner so the two never drift)."""
        micro = [next(data_iter) for _ in range(gas)]
        return jax.tree.map(lambda *xs: np.stack(xs), *micro)

    def _shard_batch(self, batch, stacked: bool):
        """Place a host batch on the mesh: [B, ...] (or [gas, B, ...]) with B split
        over the DP axes. Multi-host: each process supplies its local shard of the
        global batch (reference: distributed sampler), assembled with
        make_array_from_process_local_data."""
        multi_host = jax.process_count() > 1

        def place(x):
            x = np.asarray(x)
            spec = self.batch_spec
            if stacked:
                spec = PartitionSpec(None, *spec)
            sharding = NamedSharding(self.mesh, spec)
            if multi_host:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)
        tr = self.tracer
        nbytes = sum(int(getattr(x, "nbytes", 0))
                     for x in jax.tree.leaves(batch)) if tr.enabled else 0
        with tr.span("comm/h2d", cat="comm", bytes=nbytes):
            return jax.tree.map(place, batch)

    def train_batch(self, data_iter: Optional[Iterator] = None,
                    batch: Optional[Any] = None, stacked: Optional[bool] = None) -> jnp.ndarray:
        """Run one full training batch (gas microbatches + optimizer update) as one
        compiled step. Pass either an iterator yielding microbatches (reference
        ``PipelineEngine.train_batch`` contract) or ``batch`` whose leaves are
        stacked [gas, micro_global, ...]. When gas == 1 an unstacked
        [micro_global, ...] batch is accepted (``stacked=True`` overrides)."""
        gas = self.gradient_accumulation_steps
        fused_path = self._param_offload is None and self._offload is None
        if batch is None:
            if data_iter is None:
                raise ValueError("train_batch needs data_iter or batch")
            if self._prefetch_enabled and fused_path:
                # background double buffer: stack + device_put happen one
                # step ahead, so batch N+1's H2D overlaps batch N's compute
                batch = next(self._ensure_prefetcher(data_iter))
            else:
                batch = self.stack_microbatches(data_iter, gas)
        elif gas == 1 and not stacked and not isinstance(batch, StagedBatch):
            # deterministic rule (no shape-guessing): gas==1 batches are unstacked
            # unless the caller says otherwise
            batch = jax.tree.map(lambda x: np.asarray(x)[None], batch)
        # rare host-side consumers (profiler/eigenvalue) read through the wrapper
        host_view = batch.arrays if isinstance(batch, StagedBatch) else batch
        if (self.config.flops_profiler.enabled
                and self.global_steps == self.config.flops_profiler.profile_step):
            self._run_flops_profile(host_view)
        if self._param_offload is not None:
            return self._train_batch_param_offload(host_view)
        if self._offload is not None:
            return self._train_batch_offloaded(host_view)
        if self._train_batch_fn is None:
            self._build_train_batch_fn()
        if isinstance(batch, StagedBatch):
            device_batch = batch.arrays    # prefetch thread already staged it
        else:
            device_batch = self._shard_batch(batch, stacked=True)
        self._rng, step_rng = jax.random.split(self._rng)

        # async mode times *dispatch* per step (no completion wait); the true
        # step time is reconciled into TRAIN_BATCH_TIMER at each ring drain
        step_timer = self.timers(TRAIN_BATCH_DISPATCH_TIMER
                                 if self._async_enabled else TRAIN_BATCH_TIMER)
        if self._async_enabled and not self._metric_ring:
            # empty ring = a fresh window: anchor it at this dispatch, so
            # host pauses between windows (checkpoint I/O, idle gaps after a
            # flush) are never booked as step time at the next drain
            self._last_drain_time = time.time()
        self.tput_timer.start()
        step_timer.start()
        # dispatch span: host time spent LAUNCHING the fused step (no
        # completion wait — in async mode the reconciled step time shows up
        # as engine/steps_reconciled at the drain; comparing the two is the
        # dispatch-gap-vs-step-time view the async pipeline is tuned by)
        if self._mem_sampler is not None:
            # phase transition is attribute stores (hot-path safe): the
            # first dispatched step carries compile workspace the analytic
            # plan does not model, so it gets its own observation bucket.
            # In async mode the first SAMPLE happens at the first drain
            # (up to sync_every steps later) — hold "first_step" until one
            # sample lands in it, else the bucket would be overwritten to
            # "steady" before it was ever observed; the 2x-sync_every step
            # guard bounds the hold for cadence-thread-only configs
            sampler = self._mem_sampler
            if self.global_steps == 0:
                sampler.phase = "first_step"
            elif sampler.phase == "first_step" and (
                    sampler.seen("first_step")
                    or self.global_steps >= 2 * max(self._sync_every or 1,
                                                    1)):
                sampler.phase = "steady"
        overlap_trace = (self._comm_compress is not None
                         and self.tracer.enabled)
        t_dispatch0 = time.monotonic() if overlap_trace else 0.0
        try:
            with self.tracer.span(
                    "engine/dispatch", cat="train", step=self.global_steps,
                    mode="async" if self._async_enabled else "sync"):
                self.state, out = self._train_batch_fn(self.state,
                                                       device_batch,
                                                       step_rng)
        except Exception as e:
            # compile-time RESOURCE_EXHAUSTED raises at dispatch: classify
            # and stash forensics before the error unwinds (no-op otherwise)
            self._note_oom(e)
            raise
        if overlap_trace:
            self._emit_overlap_spans(t_dispatch0, time.monotonic())
        step_timer.stop()
        self.tput_timer.stop(global_step=True)

        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += self.train_batch_size
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if self.eigenvalue is not None and self.global_steps % max(
                self.eigenvalue.cfg.gas_boundary_resolution, 1) == 0:
            # reference: eigenvalue at gas boundaries feeding compression MoQ
            # (engine.py quantizer hooks); results cached on the engine
            eval_batch = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)[0]),
                                      host_view)
            self.block_eigenvalues = self.eigenvalue.compute_eigenvalue(
                lambda p: self._compute_loss(p, eval_batch,
                                             jax.random.PRNGKey(0)),
                self.state.params, jax.random.PRNGKey(self.global_steps))
        self._advance_data_schedules()
        self._record_metrics(out)
        return out.loss

    def _train_batch_param_offload(self, batch) -> jnp.ndarray:
        """ZeRO-Infinity parameter-offload step: the streamed layer-group
        fwd/bwd + fused host optimizer in runtime/param_offload.py."""
        batch_host = {k: np.asarray(v) for k, v in batch.items()}
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        applied_step = self.global_steps   # the step the offload optimizer
        with self.tracer.span("engine/train_step", cat="train",
                              step=applied_step, mode="param_offload"):
            loss, norm = self._param_offload.train_batch(  # evaluates lr at
                batch_host, step=applied_step)
        self.timers(TRAIN_BATCH_TIMER).stop()
        self.tput_timer.stop(global_step=True)
        self.state = self.state._replace(step=self.state.step + 1)
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps
        self.global_samples += self.train_batch_size
        self._advance_data_schedules()
        # report the lr that was ACTUALLY applied (pre-increment step), not
        # the next step's schedule value
        lr = float(jax.device_get(self.lr_schedule(jnp.int32(applied_step))))
        self._record_metrics(StepOutput(
            loss=jnp.float32(loss), grad_norm=jnp.float32(norm),
            lr=jnp.float32(lr), overflow=jnp.bool_(False)), sync=True)
        # stream observability: H2D volume + phase split (monitor fan-out
        # picks these up alongside the standard Train/Samples events)
        self._last_metrics["param_offload_bytes_streamed"] = float(
            self._param_offload.bytes_streamed)
        for phase, secs in self._param_offload.phase_seconds.items():
            self._last_metrics[f"param_offload_{phase}_s"] = secs
        return jnp.float32(loss)

    def _train_batch_offloaded(self, batch) -> jnp.ndarray:
        """ZeRO-Offload step: device grads under jit, fused C++ host optimizer on
        fp32 masters, compute-dtype shadow back to device (reference: CPU
        optimizer step stage3.py:964 with offload). The device<->host round trip
        is the cost the reference pays too; overlap comes from the async swapper
        inside. fp16 loss scaling + overflow step-skip match the in-HBM path."""
        cfg = self.config
        if self._offload_grad_fn is None:
            gas = self.gradient_accumulation_steps
            fp16 = cfg.fp16

            grads_phase = self._make_grads_phase()

            def grad_step(params, stacked_batch, rng, scale):
                rngs = jax.random.split(rng, gas)
                loss, grads = grads_phase(params, stacked_batch, rngs, scale)
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32) / (scale * gas), grads)
                overflow = precision.has_inf_or_nan(grads) \
                    if (fp16.enabled or self._guard_nonfinite) \
                    else jnp.bool_(False)
                if cfg.gradient_clipping > 0:
                    grads, norm = precision.clip_by_global_norm(
                        grads, cfg.gradient_clipping)
                else:
                    norm = precision.global_grad_norm(grads)
                return loss, grads, norm, overflow

            self._offload_grad_fn = watch_jit(jax.jit(grad_step),
                                              "engine.offload_grad_step")

        device_batch = self._shard_batch(batch, stacked=True)
        self._rng, r = jax.random.split(self._rng)
        self.tput_timer.start()
        with self.tracer.span("engine/train_step", cat="train",
                              step=self.global_steps, mode="offload"):
            loss, grads, norm, overflow = self._offload_grad_fn(
                self.state.params, device_batch, r,
                self.state.loss_scale.scale)
            self._offload_host_update(loss, grads, norm, overflow)
        self.tput_timer.stop(global_step=True)
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps
        self.global_samples += self.train_batch_size
        self._advance_data_schedules()
        return loss

    def _offload_host_update(self, loss, grads, norm, overflow):
        """Host half of an offloaded step: on overflow skip the update and shrink
        the loss scale (parity with _update's keep_old/skip); otherwise run the
        fused CPU kernel on the masters and push a compute-dtype shadow back."""
        cfg = self.config
        overflow_host = bool(jax.device_get(overflow))
        lr = float(jax.device_get(self.lr_schedule(self.state.step)))
        new_scale = precision.update_loss_scale(
            self.state.loss_scale, overflow, cfg.fp16) if cfg.fp16.enabled \
            else self.state.loss_scale
        if overflow_host:
            self.state = self.state._replace(
                loss_scale=new_scale,
                skipped_steps=self.state.skipped_steps + 1)
        else:
            grads_host = [np.asarray(jax.device_get(g))
                          for g in jax.tree.leaves(grads)]
            self._offload.step(grads_host, lr=lr)
            shadow = self._offload.shadows(np.dtype(self.compute_dtype).name)
            new_params = jax.tree_util.tree_unflatten(self._params_treedef, shadow)
            self.state = self.state._replace(
                params=jax.device_put(new_params, self.param_shardings),
                step=self.state.step + 1,
                loss_scale=new_scale)
        self._record_metrics(StepOutput(loss=loss, grad_norm=norm,
                                        lr=jnp.float32(lr), overflow=overflow),
                             sync=True)

    def set_nonfinite_guard(self, enabled: bool = True) -> None:
        """Arm/disarm the resilience step guard: with it armed, non-finite
        grads are treated exactly like an fp16 overflow in every precision
        mode — the update is dropped, params stay at the last good step, and
        ``skipped_steps`` increments (reference: CheckOverflow generalized
        past the loss scaler). Toggling re-traces the compiled step."""
        enabled = bool(enabled)
        if self._guard_nonfinite != enabled:
            self._guard_nonfinite = enabled
            self._reset_compiled_fns()
            log_dist(f"non-finite step guard {'armed' if enabled else 'off'}",
                     ranks=[0])

    def _emit_overlap_spans(self, t0: float, t1: float) -> None:
        """Per-bucket ``comm/overlap`` retro-spans on the dedicated
        synthetic track (tracer.COMM_OVERLAP_TID): the analytic schedule of
        the bucketed quantized reductions inside the dispatched step — the
        window [t0, t1] split proportionally by each bucket's wire bytes.
        Off the main track by construction, so ``dstpu plan`` attributes
        the time as overlapped comm (overlap_fraction) rather than step
        cost, exactly the treatment the prefetch worker's staging gets.
        Hot-path registered: appends only, no device touch."""
        from deepspeed_tpu.telemetry.tracer import COMM_OVERLAP_TID
        comp = self._comm_compress
        window = max(t1 - t0, 0.0)
        end = t0
        for b in self._overlap_meta:
            dur = window * (b["wire_bytes"] / self._overlap_wire_total)
            end += dur
            self.tracer.complete(
                "comm/overlap", dur, cat="comm", end_ts=end,
                tid=COMM_OVERLAP_TID, bucket=b["index"], bytes=b["bytes"],
                wire_bytes=b["wire_bytes"], world=comp.world,
                op="quantized_all_reduce", step=self.global_steps)

    def dump_trace(self, path: Optional[str] = None,
                   tail_s: Optional[float] = None) -> Dict[str, Any]:
        """Write (and return) the dstrace Chrome-trace dump — dispatch /
        drain / prefetch / checkpoint / comm spans plus resilience instant
        events, loadable in ui.perfetto.dev. ``tail_s`` restricts to the
        trailing slice. Also reachable hands-off via ``DSTPU_TRACE=path``
        (dump at exit). See docs/observability.md."""
        return self.tracer.export_chrome(path, tail_s=tail_s)

    def trace_summary(self, prefix: Optional[str] = None) -> Dict[str, Any]:
        """Per-span aggregate (count/total/mean/max/p50/p95/p99 seconds) of
        the tracer ring — the quick in-process look before dumping a
        trace; ``dstpu plan`` on a dump is the full attribution view."""
        return self.tracer.summary(prefix=prefix)

    # ------------------------------------------------------------------
    # dsmem: analytic ledger, live watermarks, OOM forensics
    # ------------------------------------------------------------------
    def _param_count(self) -> int:
        """Model parameter count from host-side metadata (leaf shapes —
        never a device transfer). Under offload_param the device params
        tuple is empty; count the host masters instead."""
        if self._param_offload is not None:
            import math
            try:
                return sum(math.prod(leaf.shape)
                           for leaf in self._param_offload.opt.leaves)
            except Exception:
                return 0
        return sum(int(getattr(x, "size", 0))
                   for x in jax.tree_util.tree_leaves(self.state.params))

    def memory_ledger(self):
        """The analytic dsmem plan for THIS engine's config + mesh (see
        ``deepspeed_tpu/telemetry/memory.py``): per-component bytes and
        per-phase expected HBM/host watermarks. Activation terms need
        shape hints the engine cannot infer generically — model states
        (the dominant preflight term) are exact."""
        from deepspeed_tpu.telemetry.memory import MemoryLedger
        return MemoryLedger.from_config(
            self.config.raw(), num_params=self._param_count(),
            mesh_shape={str(k): int(v) for k, v in self.mesh.shape.items()})

    def _memory_preflight(self, policy: str) -> None:
        """Analytic plan vs device ``bytes_limit`` BEFORE training: a plan
        that cannot fit warns (or raises, ``preflight: refuse``) with the
        next offload tier instead of dying minutes later in XLA with a
        RESOURCE_EXHAUSTED. Skipped on backends without allocator stats
        (CPU: ``memory_stats() is None``)."""
        from deepspeed_tpu.telemetry.memory import (MemoryPreflightError,
                                                    preflight)
        try:
            ledger = self.memory_ledger()
        except Exception:
            logger.exception("dsmem: preflight ledger construction failed")
            return
        limit = 0
        try:
            for s in self.accelerator.memory_stats().values():
                limit = max(limit, int(s.get("bytes_limit", 0)))
        except Exception:
            pass
        if not limit:
            log_dist("dsmem: device reports no bytes_limit (CPU backend?) "
                     "— analytic preflight skipped", ranks=[0])
            return
        verdict = preflight(ledger, limit)
        if verdict["fits"] and not verdict["tight"]:
            return
        sug = verdict.get("suggestion") or {}
        msg = (f"dsmem preflight: plan needs "
               f"{verdict['required_bytes'] / 1e9:.2f}GB HBM at the "
               f"'{verdict['worst_phase']}' watermark vs device limit "
               f"{limit / 1e9:.2f}GB")
        if sug:
            msg += (f"; next tier: {sug['suggestion']} "
                    f"(overrides: {sug['overrides']})")
        if not verdict["fits"] and policy == "refuse":
            raise MemoryPreflightError(msg)
        log_dist(("WARNING: " if not verdict["fits"]
                  else "dsmem preflight (tight headroom): ") + msg,
                 ranks=[0])

    def memory_forensics(self, error: Optional[str] = None,
                         samples: int = 32) -> Dict[str, Any]:
        """Everything the OOM diagnostic bundle embeds: the analytic
        ledger, the last N live samples, per-phase observed watermarks,
        and plan-vs-observed deltas."""
        out: Dict[str, Any] = {
            "error": (error or "")[:2000] or None,
            "global_steps": self.global_steps,
        }
        plan: Dict[str, Any] = {}
        try:
            ledger = self.memory_ledger()
            out["ledger"] = ledger.to_dict()
            plan = ledger.phase_bytes()
        except Exception:
            logger.exception("dsmem: forensics ledger failed")
        if self._mem_sampler is not None:
            # one last observation so the bundle carries the dying state
            try:
                self._mem_sampler.sample(step=self.global_steps)
            except Exception:
                pass
            wm = self._mem_sampler.watermarks()
            out["watermarks"] = wm
            out["samples"] = self._mem_sampler.tail(samples)
            deltas = {}
            for phase, obs in wm.items():
                p = plan.get(phase, {}).get("hbm_bytes")
                o = obs.get("hbm_peak_bytes") or obs.get("hbm_bytes_in_use")
                if p and o:
                    deltas[phase] = round(o / p - 1.0, 4)
            out["plan_vs_observed_delta_frac"] = deltas
        return out

    def _note_oom(self, exc: BaseException) -> None:
        """Dispatch/drain error hook: when the failure classifies as
        RESOURCE_EXHAUSTED, stamp the timeline and stash the forensics
        dict on ``engine.last_oom`` (the resilience runner folds it into
        the diagnostic bundle). Non-OOM errors pass through untouched."""
        from deepspeed_tpu.telemetry.memory import is_oom_error
        if not is_oom_error(exc):
            return
        self.tracer.instant("mem/oom", cat="mem", step=self.global_steps)
        self.last_oom = self.memory_forensics(error=str(exc))
        logger.error("engine: RESOURCE_EXHAUSTED at step %d — memory "
                     "forensics stashed on engine.last_oom",
                     self.global_steps)

    def dump_memory_report(self, path: Optional[str] = None
                           ) -> Dict[str, Any]:
        """Write (and return) the dsmem report artifact — plan + observed
        per-phase watermarks — the input of ``bin/dstpu mem`` (tie-out +
        watermark ratchet vs ``mem_baseline.json``)."""
        from deepspeed_tpu.telemetry.memory import MemorySampler
        sampler = self._mem_sampler
        if sampler is None:
            sampler = MemorySampler(tracer=self.tracer)
        if not sampler.samples:
            sampler.sample(step=self.global_steps)
        try:
            ledger = self.memory_ledger()
        except Exception:
            logger.exception("dsmem: report ledger failed")
            ledger = None
        if path:
            return sampler.export(path, ledger=ledger)
        return sampler.report(ledger=ledger)

    def start_profile_trace(self, log_dir: str) -> None:
        """Start an XLA/TPU profiler trace (reference: NVTX ranges + torch
        profiler hooks; here jax.profiler writes a TensorBoard-viewable trace
        with the engine's named timer scopes)."""
        jax.profiler.start_trace(log_dir)
        log_dist(f"profiler trace started -> {log_dir}", ranks=[0])

    def stop_profile_trace(self) -> None:
        jax.profiler.stop_trace()
        log_dist("profiler trace stopped", ranks=[0])

    def _run_flops_profile(self, stacked_batch):
        """Profile the forward pass at ``profile_step`` (reference: engine.py:1850
        auto-invokes FlopsProfiler). Abstract trace only — no extra device work."""
        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
        fcfg = self.config.flops_profiler
        micro = jax.tree.map(lambda x: np.asarray(x)[0], stacked_batch)
        prof = FlopsProfiler(self._compute_loss, params=self.state.params)
        prof.stop_profile(self.state.params, micro, self._rng)  # abstract trace only
        prof.print_model_profile(profile_step=self.global_steps,
                                 module_depth=fcfg.module_depth,
                                 top_modules=fcfg.top_modules,
                                 detailed=fcfg.detailed,
                                 output_file=fcfg.output_file)
        self.flops_profiler = prof

    def _advance_data_schedules(self):
        """Advance curriculum/random-LTD schedules at each global step (reference:
        engine curriculum updates + data_pipeline schedulers)."""
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.update_difficulty(self.global_steps)
        if self.random_ltd_scheduler is not None:
            self.random_ltd_scheduler.update_seq(self.global_steps)
        if self.compressor is not None:
            self.compressor.set_step(self.global_steps)
            self.compressor.maybe_freeze_masks(self.state.params)
            key = self.compressor.schedule_key()
            if key != self._compression_key:
                # schedule transition (technique activated / bits annealed):
                # drop every compiled step so the next call re-traces with the
                # new static compression structure
                self._compression_key = key
                self._reset_compiled_fns()

    def set_custom_curriculum_learning_schedule(self, schedule_fn):
        """reference: engine.set_custom_curriculum_learning_schedule — install a
        user difficulty function for 'custom' schedule_type."""
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.set_custom_get_difficulty(schedule_fn)

    def curriculum_seqlen(self) -> int:
        """Current legacy-curriculum difficulty (seqlen); full seq when disabled."""
        if self.curriculum_scheduler is None:
            raise RuntimeError("curriculum_learning not enabled in config")
        return self.curriculum_scheduler.get_current_difficulty()

    def random_ltd_reserved_length(self) -> int:
        if self.random_ltd_scheduler is None:
            raise RuntimeError("random_ltd not enabled in config")
        return self.random_ltd_scheduler.get_current_seq()

    def _record_metrics(self, out: StepOutput, sync: bool = False):
        """Step-output fan-out. Async pipeline OFF (default) or ``sync=True``
        (host-offload / compat paths, which are host-synchronous by
        construction): today's per-step semantics, device-array
        ``_last_metrics`` + monitor floats at ``steps_per_print`` boundaries.
        Async pipeline ON: the outputs queue on the device-side ring —
        NOTHING is transferred here — and the ring drains (one batched
        ``device_get``) every ``sync_every`` steps."""
        if self._async_enabled and not sync:
            # NOTE: only StepOutput arrays are queued — they are fresh jit
            # outputs. EngineState buffers (e.g. loss_scale.scale) must NOT
            # be captured here: the state is donated to the next compiled
            # step, which deletes those buffers while they'd still sit in
            # the ring. The live scale is fetched at drain time instead.
            due = self._sched.push({
                "step": self.global_steps,
                "samples": self.global_samples,
                "loss": out.loss, "grad_norm": out.grad_norm, "lr": out.lr,
                "overflow": out.overflow,
            })
            if due:
                self._drain_metric_ring()
            return
        self._last_metrics = {"lr": out.lr, "grad_norm": out.grad_norm,
                              "loss": out.loss, "overflow": out.overflow}
        if self._mem_sampler is not None \
                and self.config.memory.sample_on_drain:
            # sync/host-offload paths reach here after the step counter
            # incremented — derive the phase from it (the fused path set it
            # at dispatch; offload paths never dispatch through there)
            self._mem_sampler.phase = ("first_step" if self.global_steps <= 1
                                       else "steady")
            if (self.global_steps % self.config.steps_per_print == 0
                    or not self._mem_sampler.seen(self._mem_sampler.phase)):
                # the print boundary is sync mode's step-boundary sampling
                # cadence (already a host-visible boundary), plus each
                # phase's first step so short runs cover every bucket
                self._mem_sampler.on_drain(step=self.global_steps)
        if self.monitor and self.monitor.enabled:
            events = self._monitor_step_events(
                self.global_steps, self.global_samples, out.loss, out.lr,
                self.state.loss_scale.scale)
            if events:
                self.monitor.write_events(events)

    def _monitor_step_events(self, step, samples, loss, lr, loss_scale):
        """Train/Samples events for one step, gated on the steps_per_print
        boundary — THE single source for both the synchronous record path
        and the async drain (so the two can never log different metrics)."""
        if step % self.config.steps_per_print != 0:
            return []
        events = [("Train/Samples/train_loss", float(loss), samples),
                  ("Train/Samples/lr", float(lr), samples)]
        if self.config.fp16.enabled:
            events.append(("Train/Samples/loss_scale", float(loss_scale),
                           samples))
        return events

    # ------------------------------------------------------------------
    # async step pipeline: the designated drain + its consumers
    # ------------------------------------------------------------------
    # The ring/prefetcher mechanics live on the shared sched core
    # (runtime/sched.py, also consumed by the serve loop); these views keep
    # the names the PR 3 pipeline exposed — consumers and the hot-sync
    # lint fixtures poke them directly.
    @property
    def _metric_ring(self) -> List[Dict[str, Any]]:
        return self._sched.pending

    @property
    def _drained_metrics(self) -> collections.deque:
        return self._sched.drained

    @property
    def _last_drain_time(self) -> Optional[float]:
        return self._sched.anchor

    @_last_drain_time.setter
    def _last_drain_time(self, t: Optional[float]) -> None:
        self._sched.anchor = t

    @property
    def _sync_every(self) -> int:
        return self._sched.sync_every

    @_sync_every.setter
    def _sync_every(self, v: int) -> None:
        self._sched.sync_every = int(v)

    @property
    def _prefetch_depth(self) -> int:
        return self._staged.depth

    @_prefetch_depth.setter
    def _prefetch_depth(self, v: int) -> None:
        self._staged.depth = int(v)

    @property
    def _prefetcher(self) -> Optional[PrefetchLoader]:
        return self._staged.loader

    @property
    def _prefetcher_src(self):
        return self._staged.source

    @property
    def _prefetch_switches(self) -> int:
        return self._staged.switches

    def _drain_metric_ring(self) -> List[Dict[str, Any]]:
        """THE designated readback point of the async pipeline: one batched
        ``device_get`` (DispatchRing.drain) moves every pending step's
        outputs to host (and, by data dependency, proves those steps'
        device work completed — the anchor that keeps the reconciled timers
        honest). Host fan-out: ``_last_metrics``, monitor events for
        ``steps_per_print``-boundary steps, TRAIN_BATCH_TIMER/throughput
        reconciliation, and the ordered entry queue the resilience runner
        replays through its StepGuard."""
        # the LIVE loss scale rides the same transfer (exact at sync_every=1;
        # for lagged fp16 entries the monitor shows the drain-time scale);
        # execution-time OOM of an async step surfaces at the designated
        # readback — same classify-and-stash contract
        try:
            res = self._sched.drain(extra=self.state.loss_scale.scale)
        except Exception as e:
            self._note_oom(e)
            raise
        if res is None:
            return []
        scale = float(res.extra)
        entries = [{"step": int(e["step"]), "samples": int(e["samples"]),
                    "loss": float(e["loss"]),
                    "grad_norm": float(e["grad_norm"]),
                    "lr": float(e["lr"]), "overflow": bool(e["overflow"]),
                    "loss_scale": scale} for e in res.payloads]
        last = entries[-1]
        self._last_metrics = {"lr": last["lr"], "grad_norm": last["grad_norm"],
                              "loss": last["loss"],
                              "overflow": last["overflow"]}
        # window anchor = dispatch of this window's FIRST step (train_batch
        # re-anchors whenever the ring is empty), so checkpoint I/O or idle
        # gaps between windows never inflate the reconciled step time
        window = 0.0
        if res.anchored:
            window = res.window_s
            self.timers(TRAIN_BATCH_TIMER).record_external(
                window, count=len(entries))
            # retro span covering the reconciled window: the TRUE step time
            # of the drained steps (dispatch spans only show launch cost)
            self.tracer.complete("engine/steps_reconciled", window,
                                 cat="train", steps=len(entries),
                                 last_step=last["step"])
        for e in entries:
            if e["overflow"]:
                self.tracer.instant("engine/overflow_step", cat="train",
                                    step=e["step"])
        self.tput_timer.mark_edge()
        if self.monitor and self.monitor.enabled:
            events = []
            for e in entries:
                events.extend(self._monitor_step_events(
                    e["step"], e["samples"], e["loss"], e["lr"],
                    e["loss_scale"]))
            if window > 0:
                events.append(("Train/Samples/steps_per_sec",
                               len(entries) / window, last["samples"]))
            if events:
                self.monitor.write_events(events)
        if self._mem_sampler is not None and self.config.memory.sample_on_drain:
            # the drain already paid a host sync; the dsmem sample here adds
            # allocator-stat dict reads only (DS002-registered hook)
            self._mem_sampler.on_drain(step=last["step"])
        self._sched.store(entries)
        return entries

    def flush_metrics(self) -> List[Dict[str, Any]]:
        """Force-drain the deferred step-output ring (one batched device_get);
        returns the newly drained host entries, [] when nothing is pending.
        Callers use it as a barrier at log/checkpoint boundaries — the
        resilience runner flushes before every save so a checkpoint never
        captures steps its guard has not judged."""
        return self._drain_metric_ring()

    def take_drained_metrics(self) -> List[Dict[str, Any]]:
        """Pop the drained-but-unconsumed host metric entries (ordered, one
        per step: step/samples/loss/grad_norm/lr/overflow/loss_scale). The
        resilience runner's per-step hook — with ``sync_every=N`` its guard
        observes steps with up to N steps of detection lag, replayed in
        order here."""
        return self._sched.take()

    def requeue_drained_metrics(self, entries: List[Dict[str, Any]]) -> None:
        """Put taken-but-unprocessed entries back at the FRONT of the queue
        (original order preserved) — the runner uses this when its guard
        raises mid-replay, so the tail still gets judged by a later flush."""
        self._sched.requeue(entries)

    def configure_async_pipeline(self, enabled: Optional[bool] = None,
                                 sync_every: Optional[int] = None,
                                 prefetch: Optional[bool] = None,
                                 prefetch_depth: Optional[int] = None):
        """Reconfigure the latency-hiding pipeline at runtime (bench sweeps,
        notebooks). The pending ring is flushed FIRST so no step crosses a
        semantics change un-drained. Closing an active prefetcher drops its
        staged batches (the source iterator has already advanced past them)
        — reconfigure at iterator boundaries when exact batch order matters."""
        self.flush_metrics()
        self._staged.close()
        if enabled is not None:
            if enabled and (self._param_offload is not None
                            or self._offload is not None):
                raise ValueError(
                    "async_pipeline cannot be enabled on a host-offload "
                    "engine: the fused host optimizer step is synchronous "
                    "by construction")
            self._async_enabled = bool(enabled)
        if sync_every is not None:
            if int(sync_every) < 1:
                raise ValueError(f"sync_every must be >= 1, got {sync_every}")
            self._sync_every_cfg = int(sync_every)
        # an explicitly-set cadence survives toggling orthogonal knobs
        self._sync_every = self._sync_every_cfg if self._async_enabled else 1
        if prefetch is not None:
            self._prefetch_enabled = bool(prefetch)
        self._prefetch_enabled = self._prefetch_enabled and self._async_enabled
        if self._prefetch_enabled and (self.config.flops_profiler.enabled
                                       or self.config.eigenvalue.enabled):
            log_dist("async_pipeline: prefetch disabled — flops_profiler/"
                     "eigenvalue need host-materialized batches", ranks=[0])
            self._prefetch_enabled = False
        if prefetch_depth is not None:
            self._prefetch_depth = max(1, int(prefetch_depth))
        self.tput_timer.synchronize = not self._async_enabled
        self._last_drain_time = None
        return self

    def _ensure_prefetcher(self, data_iter) -> PrefetchLoader:
        """One staged-batch prefetcher per source iterator (identity-keyed
        by StagedPrefetcher; a new source closes the old prefetcher,
        dropping its staged batches — swap iterators at epoch boundaries)."""
        gas = self.gradient_accumulation_steps

        def stacked_batches():
            while True:
                try:
                    yield self.stack_microbatches(data_iter, gas)
                except StopIteration:   # PEP 479: surface as a clean end
                    return

        def build():
            return PrefetchLoader(
                stacked_batches(),
                stage_fn=lambda b: StagedBatch(
                    self._shard_batch(b, stacked=True)),
                depth=self._prefetch_depth)

        return self._staged.ensure(data_iter, build)

    # ------------------------------------------------------------------
    # forward/backward/step compatibility protocol
    # ------------------------------------------------------------------
    def _build_micro_fns(self):
        cfg = self.config
        tx, lr_schedule = self.tx, self.lr_schedule
        clip, fp16 = cfg.gradient_clipping, cfg.fp16
        grad_shardings = self.param_shardings

        acc_dtype = cfg.grad_accum_dtype

        def fwd_bwd_local(params, batch, rng, scale):
            loss, grads = self._grads_one_micro(params, batch, rng, scale)
            # accumulate in the configured dtype (fp32 default) even when params
            # are compute-dtype shadows (offload mode)
            return loss, jax.tree.map(lambda g: g.astype(acc_dtype), grads)

        # compat path reduces per-microbatch (the reference reduces at each
        # backward when not accumulating); with replica axes the reduce is
        # the int8/sparse-wire collective, one sync per forward/backward pair
        from deepspeed_tpu.runtime.zero.qgz import wrap_grads_phase
        if self._comm_compress is not None:
            # compression without error feedback on the per-microbatch
            # shim: residuals are defined at the accumulation boundary (one
            # reduction per optimizer step), which forward/backward/step
            # does not expose — train_batch() is the EF-carrying path
            wire_axes = self._replica_axes
            _csync = self._comm_compress.make_sync_fn(
                fallback_leaf_sync=self._compress_fallback_sync(wire_axes))

            def sync_fn(grads, batch):
                reduced, _ = _csync(grads, batch, ())
                return reduced
        else:
            wire_axes = self._qgz_axes or self._sparse_grad_axes
            sync_fn = self._make_grad_sync(wire_axes)

        fwd_bwd = wrap_grads_phase(fwd_bwd_local, self.mesh, wire_axes,
                                   self.batch_spec, stacked=False,
                                   sync_fn=sync_fn)

        self._micro_fwd_bwd_fn = watch_jit(jax.jit(
            fwd_bwd, out_shardings=(None, grad_shardings)),
            "engine.micro_fwd_bwd")

        def accum(buf, grads):
            return jax.tree.map(jnp.add, buf, grads)

        self._accum_fn = watch_jit(jax.jit(accum, donate_argnums=(0,),
                                           out_shardings=grad_shardings),
                                   "engine.accum")

        def apply_update(state, grad_sum):
            gas = self.gradient_accumulation_steps
            scale = state.loss_scale.scale
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / (scale * gas), grad_sum)
            return self._update(state, grads, tx, lr_schedule, clip, fp16)

        self._apply_update_fn = watch_jit(jax.jit(
            apply_update, donate_argnums=(0, 1),
            out_shardings=(self.state_shardings, None)),
            "engine.apply_update")

    def _reject_param_offload(self, api: str):
        if self._param_offload is not None:
            raise NotImplementedError(
                f"{api} is not supported with offload_param: the streamed "
                "step cannot keep per-microbatch grads device-resident "
                "between calls — use train_batch()")

    def forward(self, batch) -> jnp.ndarray:
        """Compat shim (reference engine.forward:1838): computes loss AND caches
        grads for the subsequent backward()."""
        self._reject_param_offload("forward()")
        if self._micro_fwd_bwd_fn is None:
            self._build_micro_fns()
        self.timers(FORWARD_GLOBAL_TIMER).start()
        device_batch = self._shard_batch(batch, stacked=False)
        self._rng, r = jax.random.split(self._rng)
        loss, grads = self._micro_fwd_bwd_fn(self.state.params, device_batch, r,
                                             self.state.loss_scale.scale)
        self._pending = (loss, grads)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    def backward(self, loss=None):
        """Compat shim (reference engine.backward:1977): folds the cached microbatch
        grads into the accumulation buffer."""
        if self._pending is None:
            raise RuntimeError("backward() called without a preceding forward()")
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        _, grads = self._pending
        self._pending = None
        if self._grad_buffer is None:
            self._grad_buffer = grads
        else:
            self._grad_buffer = self._accum_fn(self._grad_buffer, grads)
        self._accum_count += 1
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._accum_count >= self.gradient_accumulation_steps

    def step(self):
        """Compat shim (reference engine.step:2176): applies the update at the
        gradient-accumulation boundary; otherwise a no-op. Routes through the
        host offload optimizer when configured (same path as train_batch)."""
        self._reject_param_offload("step()")
        if not self.is_gradient_accumulation_boundary():
            return
        self.timers(STEP_GLOBAL_TIMER).start()
        if self._offload is not None:
            if self._offload_apply_fn is None:
                cfg = self.config
                gas = self.gradient_accumulation_steps

                def finalize(grad_sum, scale):
                    grads = jax.tree.map(
                        lambda g: g.astype(jnp.float32) / (scale * gas), grad_sum)
                    overflow = precision.has_inf_or_nan(grads) \
                        if (cfg.fp16.enabled or self._guard_nonfinite) \
                        else jnp.bool_(False)
                    if cfg.gradient_clipping > 0:
                        grads, norm = precision.clip_by_global_norm(
                            grads, cfg.gradient_clipping)
                    else:
                        norm = precision.global_grad_norm(grads)
                    return grads, norm, overflow

                self._offload_apply_fn = jax.jit(finalize)
            grads, norm, overflow = self._offload_apply_fn(
                self._grad_buffer, self.state.loss_scale.scale)
            self._offload_host_update(jnp.float32(0.0), grads, norm, overflow)
        else:
            if self._apply_update_fn is None:
                self._build_micro_fns()
            self.state, out = self._apply_update_fn(self.state, self._grad_buffer)
            self._record_metrics(out, sync=True)
        self._grad_buffer = None
        self._accum_count = 0
        self.global_steps += 1
        self.global_samples += self.train_batch_size
        self._advance_data_schedules()
        self.timers(STEP_GLOBAL_TIMER).stop()

    # ------------------------------------------------------------------
    # eval
    # ------------------------------------------------------------------
    def compile(self, backend=None, **compile_kwargs):
        """API parity with reference ``engine.compile()``
        (runtime/compiler.py + engine.py compile method). jit is this
        engine's native execution model — every step is already traced once
        and compiled — so this records the request and returns."""
        self._compiled = True
        log_dist("engine.compile(): no-op — the fused train step is already "
                 "jit-compiled (XLA is the native execution model)", ranks=[0])
        return self

    def train(self, mode: bool = True):
        """Module-mode parity (reference nn.Module.train/eval): tracked for
        API compatibility; functional models take determinism via batch/rng
        inputs rather than global module state."""
        self.training = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def eval_batch(self, batch) -> jnp.ndarray:
        self._reject_param_offload("eval_batch()")
        if self._eval_fn is None:
            def ev(params, batch, rng):
                return self._compute_loss(params, batch, rng)
            self._eval_fn = jax.jit(ev)
        device_batch = self._shard_batch(batch, stacked=False)
        self._rng, r = jax.random.split(self._rng)
        return self._eval_fn(self.state.params, device_batch, r)

    # __call__ mirrors the reference's module-call-through (engine(batch) -> loss)
    def __call__(self, batch):
        return self.forward(batch)

    # ------------------------------------------------------------------
    # introspection (reference engine accessor parity)
    # ------------------------------------------------------------------
    def get_lr(self):
        return [float(jax.device_get(self.lr_schedule(self.state.step)))]

    def get_global_grad_norm(self) -> float:
        v = self._last_metrics.get("grad_norm")
        return float(jax.device_get(v)) if v is not None else 0.0

    def cur_scale(self) -> float:
        return float(jax.device_get(self.state.loss_scale.scale))

    @property
    def skipped_steps(self) -> int:
        return int(jax.device_get(self.state.skipped_steps))

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.micro_batch_size

    def get_params(self):
        if self._param_offload is not None:
            return self._param_offload.masters_tree()
        return self.state.params

    def module_state_dict(self):
        if self._param_offload is not None:
            return self._param_offload.masters_tree()
        return jax.device_get(self.state.params)

    # ------------------------------------------------------------------
    # checkpointing (full engine in deepspeed_tpu/checkpoint)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None):
        """reference: engine.save_checkpoint:3109. Writes ONE logical sharded
        checkpoint (every rank participates; reshape-on-load by construction)."""
        # checkpoint boundary = drain boundary: pending deferred metrics land
        # (monitor/timers/guard consumers) before the state is snapshotted
        sampler = self._mem_sampler
        prev_phase = None
        if sampler is not None:
            prev_phase = sampler.phase
            sampler.phase = "ckpt"     # drain-hook samples land in "ckpt"
        try:
            with self.tracer.span("ckpt/save", cat="ckpt",
                                  step=self.global_steps, tag=tag or "auto"):
                self.flush_metrics()
                from deepspeed_tpu.checkpoint.engine import \
                    save_engine_checkpoint
                return save_engine_checkpoint(self, save_dir, tag=tag,
                                              client_state=client_state or {})
        finally:
            if sampler is not None:
                # the save-time watermark (stage-3 gather buffers, orbax
                # staging) is the "ckpt" phase's ledger counterpart
                sampler.sample(step=self.global_steps)
                sampler.phase = prev_phase

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        strict_provenance: bool = True):
        """reference: engine.load_checkpoint:2763 (+_get_all_zero_checkpoints
        world-size-change handling — free here: the checkpoint is topology-free).

        Mesh-portable by construction: a checkpoint saved at world N restores
        onto this engine's mesh at world M (different dp/fsdp factorization,
        different zero stage/offload tier), re-sharding host-side from the
        parameter-atomic store. ``ds_meta.json`` provenance is checked first:
        a different *model* or a changed global batch (the sampler contract)
        raises ``CheckpointProvenanceError`` — ``strict_provenance=False``
        downgrades the batch-contract check to a warning."""
        from deepspeed_tpu.checkpoint.engine import load_engine_checkpoint
        with self.tracer.span("ckpt/load", cat="ckpt", tag=tag or "latest"):
            out = load_engine_checkpoint(
                self, load_dir, tag=tag,
                load_optimizer_states=load_optimizer_states,
                strict_provenance=strict_provenance)
        # resync data-efficiency schedules to the restored global step; replay the
        # random-LTD token accounting so consumed_layer_tokens survives resume
        if self.random_ltd_scheduler is not None:
            # live training updates at steps 1..N (after each increment); replay
            # 1..N-1 here, _advance_data_schedules covers N
            for step in range(1, self.global_steps):
                self.random_ltd_scheduler.update_seq(step)
        self._advance_data_schedules()
        if self.compressor is not None:
            # restored pruning masks are baked into compiled steps as constants
            # and are NOT part of _compression_key — always re-trace after load
            self._reset_compiled_fns()
        return out
