"""Pipeline partitioning helpers.

Reference analog: ``PipelineModule`` (``runtime/pipe/module.py:86``) with
``LayerSpec``/``TiedLayerSpec`` (:30,:77) and partition methods
``parameters|uniform|type:regex``. Here models are flax modules with stacked layer
params, so "partitioning" reduces to assigning contiguous layer ranges to stages —
balanced by count (uniform) or by parameter volume (parameters).
"""

from typing import Any, List

import jax
import numpy as np


def partition_uniform(num_layers: int, num_stages: int) -> List[int]:
    """Stage boundaries [s_0=0, ..., s_P=L], uniform by layer count
    (reference: ds_utils.partition_uniform)."""
    bounds = [0]
    for s in range(num_stages):
        bounds.append(bounds[-1] + num_layers // num_stages +
                      (1 if s < num_layers % num_stages else 0))
    return bounds


def partition_balanced(weights: List[float], num_stages: int) -> List[int]:
    """Boundaries minimizing the max per-stage weight (reference:
    partition_method='parameters' — binary search over bottleneck capacity,
    ds_utils.partition_balanced)."""
    n = len(weights)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def feasible(cap: float) -> bool:
        stages, start = 0, 0
        while start < n:
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= cap:
                end += 1
            if end == start:
                return False
            stages += 1
            start = end
        return stages <= num_stages

    lo, hi = max(weights), sum(weights)
    for _ in range(50):
        mid = (lo + hi) / 2
        if feasible(mid):
            hi = mid
        else:
            lo = mid
    # materialize boundaries greedily at capacity hi
    bounds, start = [0], 0
    for _ in range(num_stages):
        end = start
        while end < n and prefix[end + 1] - prefix[start] <= hi:
            end += 1
        bounds.append(end)
        start = end
    bounds[-1] = n
    return bounds


def layer_param_counts(stacked_params: Any) -> List[float]:
    """Per-layer parameter counts from [L, ...]-stacked leaves."""
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        return []
    num_layers = leaves[0].shape[0]
    per_layer = sum(int(np.prod(l.shape[1:])) for l in leaves)
    return [float(per_layer)] * num_layers
