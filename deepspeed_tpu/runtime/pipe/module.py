"""Pipeline partitioning helpers.

Reference analog: ``PipelineModule`` (``runtime/pipe/module.py:86``) with
``LayerSpec``/``TiedLayerSpec`` (:30,:77) and partition methods
``parameters|uniform|type:regex``. Here models are flax modules with stacked layer
params, so "partitioning" reduces to assigning contiguous layer ranges to stages —
balanced by count (uniform) or by parameter volume (parameters).
"""

from typing import Any, List

import jax
import numpy as np


def partition_uniform(num_layers: int, num_stages: int) -> List[int]:
    """Stage boundaries [s_0=0, ..., s_P=L], uniform by layer count
    (reference: ds_utils.partition_uniform)."""
    bounds = [0]
    for s in range(num_stages):
        bounds.append(bounds[-1] + num_layers // num_stages +
                      (1 if s < num_layers % num_stages else 0))
    return bounds


def partition_balanced(weights: List[float], num_stages: int) -> List[int]:
    """Boundaries minimizing the max per-stage weight (reference:
    partition_method='parameters' — binary search over bottleneck capacity,
    ds_utils.partition_balanced)."""
    n = len(weights)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def feasible(cap: float) -> bool:
        stages, start = 0, 0
        while start < n:
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= cap:
                end += 1
            if end == start:
                return False
            stages += 1
            start = end
        return stages <= num_stages

    lo, hi = max(weights), sum(weights)
    for _ in range(50):
        mid = (lo + hi) / 2
        if feasible(mid):
            hi = mid
        else:
            lo = mid
    # materialize boundaries greedily at capacity hi
    bounds, start = [0], 0
    for _ in range(num_stages):
        end = start
        while end < n and prefix[end + 1] - prefix[start] <= hi:
            end += 1
        bounds.append(end)
        start = end
    bounds[-1] = n
    return bounds


def layer_param_counts(stacked_params: Any) -> List[float]:
    """Per-layer parameter counts from [L, ...]-stacked leaves."""
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        return []
    num_layers = leaves[0].shape[0]
    per_layer = sum(int(np.prod(l.shape[1:])) for l in leaves)
    return [float(per_layer)] * num_layers


def llama_pipe_module(cfg, params):
    """PipeModule adapter for the llama family — the ``PipelineModule``
    analog for GPT-style stacks (reference: ``runtime/pipe/module.py:86``
    builds stage partitions from LayerSpecs; here the flax ``scan_layers``
    layout already stacks layer params [L, ...], so the adapter just splits
    the tree into (stacked blocks, tied embed/norm/head) and binds the
    stage functions).

    ``cfg``: LlamaConfig with ``scan_layers=True``; ``params``: the
    ``LlamaForCausalLM.init`` tree. Works for any llama-family variant that
    shares the block structure (llama/mistral/qwen2/gemma configs).
    """
    import jax.numpy as jnp

    from deepspeed_tpu.models.llama import (REMAT_POLICIES, LlamaBlock,
                                            RMSNorm)
    from deepspeed_tpu.runtime.pipe.engine import PipeModule

    p = params.get("params", params)
    model = p["model"]
    if not cfg.scan_layers:
        raise ValueError("llama_pipe_module needs cfg.scan_layers=True "
                         "([L, ...]-stacked layer params)")
    stacked = model["layers"]
    tied = {"embed": model["embed"], "final_norm": model["final_norm"]}
    if not cfg.tie_embeddings:
        tied["lm_head"] = model["lm_head"]

    block = LlamaBlock(cfg)
    norm = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                   scale_offset=cfg.rms_scale_offset)

    def block_apply(layer_params, x, positions):
        return block.apply({"params": layer_params}, x, positions)
    if cfg.remat:
        # same knob as LlamaModel: per-block rematerialization bounds the
        # residual memory of the stage's vjp to one layer at a time (the
        # executor already recomputes the stage forward from its saved
        # input; remat further shrinks the recompute's own residual set).
        # prevent_cse=False as in LlamaModel's scan_layers path — the scan
        # makes the CSE barrier unnecessary and it only costs optimization
        block_apply = jax.checkpoint(
            block_apply, policy=REMAT_POLICIES[cfg.remat_policy],
            prevent_cse=False)

    def block_fn(layer_params, x):
        positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])
        return block_apply(layer_params, x, positions)

    def first_fn(tied_p, tokens):
        x = tied_p["embed"]["embedding"].astype(cfg.dtype)[tokens]
        if cfg.scale_embeddings:
            x = x * jnp.sqrt(
                jnp.asarray(cfg.hidden_size, jnp.float32)).astype(x.dtype)
        return x

    def last_fn(tied_p, y, tokens):
        x = norm.apply({"params": tied_p["final_norm"]}, y)
        if cfg.loss_chunk_size:
            # same fused head-matmul + CE chunking as the dense model's
            # _chunked_loss: fp32 logits never materialize at [B,S,V]
            from deepspeed_tpu.sequence.cross_entropy import (
                chunked_cross_entropy)
            labels = jnp.pad(tokens[:, 1:], ((0, 0), (0, 1)))
            mask = jnp.pad(jnp.ones_like(tokens[:, 1:]), ((0, 0), (0, 1)))
            head = tied_p["embed"]["embedding"] if cfg.tie_embeddings \
                else tied_p["lm_head"]["kernel"]
            kw = {"embedding": head} if cfg.tie_embeddings \
                else {"kernel": head}
            return chunked_cross_entropy(
                x, labels, mask, chunk_size=cfg.loss_chunk_size,
                soft_cap=cfg.logits_soft_cap, compute_dtype=cfg.dtype,
                unroll=getattr(cfg, "loss_chunk_unroll", False), **kw)
        if cfg.tie_embeddings:
            logits = x.astype(cfg.dtype) @ \
                tied_p["embed"]["embedding"].astype(cfg.dtype).T
        else:
            logits = x.astype(cfg.dtype) @ \
                tied_p["lm_head"]["kernel"].astype(cfg.dtype)
        logits = logits.astype(jnp.float32)
        if cfg.logits_soft_cap:
            logits = cfg.logits_soft_cap * jnp.tanh(
                logits / cfg.logits_soft_cap)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        ll = jnp.take_along_axis(logp, tokens[:, 1:][..., None],
                                 axis=-1)[..., 0]
        return -jnp.mean(ll)

    return PipeModule(block_fn=block_fn, first_fn=first_fn, last_fn=last_fn,
                      stacked_params=stacked, tied_params=tied)


def llama_params_from_pipe(cfg, stacked_params, tied_params):
    """Inverse of :func:`llama_pipe_module`'s tree split: rebuild the
    ``LlamaForCausalLM`` (scan_layers) param tree from a pipeline engine's
    stacked + tied state — the cross-topology restore path (a PP training
    run's weights load into a dense/ZeRO engine or the serving stack;
    reference: the universal checkpoint consolidates pp-rank shards the
    same way)."""
    model = {"layers": stacked_params,
             "embed": tied_params["embed"],
             "final_norm": tied_params["final_norm"]}
    if not cfg.tie_embeddings:
        model["lm_head"] = tied_params["lm_head"]
    return {"params": {"model": model}}
