"""Pipeline training engine — the PipelineEngine.train_batch analog.

Reference analog: ``deepspeed/runtime/pipe/engine.py:61`` (``PipelineEngine``:
owns the 1F1B schedule execution, grad reduction, tied-grad reduction, and the
optimizer step; call stack SURVEY.md §3.3).

TPU shape: one jitted step = 1F1B executor (``one_f_one_b.py``, a shard_map
over the ``pipe`` axis) + gradient clipping + optax update, with stage
parameters sharded ``P("pipe", ...)`` (each stage's optimizer state lives with
its layers — the reference's per-stage optimizer) and tied parameters
replicated. The module contract mirrors ``PipelineModule``: a stacked-layer
``block_fn`` plus the embedding/head ``first_fn``/``last_fn`` pair over tied
parameters.
"""

import dataclasses
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.runtime.pipe.one_f_one_b import pipeline_train_step_1f1b
from deepspeed_tpu.runtime.pipe.spmd import stack_to_stages, unstack_stages
from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class PipeModule:
    """The PipelineModule analog (reference: runtime/pipe/module.py:86).

    block_fn(layer_params, x) -> x      one transformer layer
    first_fn(tied, tokens) -> x         stage-0 embedding
    last_fn(tied, y, tokens) -> loss    last-stage head + per-microbatch loss
    stacked_params: leaves [L, ...]     (flax nn.scan layout)
    tied_params: pytree                 replicated, grads reduced across stages
    """
    block_fn: Callable
    first_fn: Callable
    last_fn: Callable
    stacked_params: Any
    tied_params: Any


class PipelineEngine:
    """train_batch over a PipeModule (reference PipelineEngine.train_batch,
    engine.py:338)."""

    def __init__(self, module: PipeModule, config: Optional[Dict] = None,
                 mesh=None, client_optimizer=None, lr_scheduler=None):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        dscfg = config if isinstance(config, DeepSpeedTPUConfig) \
            else DeepSpeedTPUConfig(config or {})
        cfg = dscfg.raw()
        self.module = module
        self.mesh = mesh or mesh_lib.get_global_mesh()
        if self.mesh is None:
            raise ValueError("PipelineEngine needs a mesh with a 'pipe' axis")
        self.num_stages = self.mesh.shape.get("pipe", 1)
        # batch triple reconciliation, same rules as the main engine
        # (reference _configure_train_batch_size); the legacy 'micro_batches'
        # key takes precedence for direct construction
        dscfg.resolve_batch_sizes(
            mesh_lib.get_data_parallel_world_size(self.mesh))
        self.micro_batches = int(cfg.get("micro_batches")
                                 or dscfg.gradient_accumulation_steps)
        self.micro_batch_size = dscfg.train_micro_batch_size_per_gpu
        opt_cfg = cfg.get("optimizer", {"type": "AdamW",
                                        "params": {"lr": 1e-3}})
        lr = float(opt_cfg.get("params", {}).get("lr", 1e-3))
        wd = float(opt_cfg.get("params", {}).get("weight_decay", 0.0))
        self.clip = float(cfg.get("gradient_clipping", 0.0))
        if client_optimizer is not None:
            # reference parity: initialize(optimizer=...) overrides the
            # config-built optimizer (an optax GradientTransformation here)
            if lr_scheduler is not None:
                raise ValueError(
                    "pipeline: a client optimizer and an lr_scheduler can't "
                    "be combined (optax binds the schedule inside the "
                    "optimizer) — pass the schedule as the optimizer's "
                    "learning_rate instead")
            self.tx = client_optimizer
        else:
            lr_arg = lr_scheduler if callable(lr_scheduler) else lr
            self.tx = optax.adamw(lr_arg, weight_decay=wd) \
                if opt_cfg.get("type", "AdamW").lower() in ("adam", "adamw") \
                else optax.sgd(lr_arg)

        # stage-sharded layout: stacked leaves [P, L/P, ...] over pipe, tied
        # replicated (reference: per-stage parameter/optimizer ownership)
        staged = stack_to_stages(module.stacked_params, self.num_stages) \
            if self.num_stages > 1 else module.stacked_params
        self._staged_spec = jax.tree.map(
            lambda x: NamedSharding(self.mesh, P("pipe",
                                                 *([None] * (x.ndim - 1))))
            if self.num_stages > 1 else NamedSharding(self.mesh, P()), staged)
        # host round-trip so the engine owns FRESH device buffers: the step
        # fn donates params, and device_put can alias the caller's arrays —
        # donating an alias would delete the user's params tree under them
        self.staged_params = jax.device_put(
            jax.tree.map(np.asarray, staged), self._staged_spec)
        self.tied_params = jax.device_put(
            jax.tree.map(np.asarray, module.tied_params),
            jax.tree.map(lambda x: NamedSharding(self.mesh, P()),
                         module.tied_params))
        self.opt_state = self.tx.init((self.staged_params, self.tied_params))
        self.global_steps = 0
        self.global_samples = 0
        self._step_fn = None
        self._eval_fn = None
        # throughput + monitor parity with the main engine (reference
        # PipelineEngine inherits both); the timer's batch size is corrected
        # to the actual batch on the first train_batch
        from deepspeed_tpu.utils.timer import ThroughputTimer
        self.steps_per_print = dscfg.steps_per_print
        self.tput_timer = ThroughputTimer(
            batch_size=(self.micro_batch_size or 1) * self.micro_batches,
            steps_per_output=self.steps_per_print)
        self.monitor = None
        if (dscfg.tensorboard.enabled or dscfg.csv_monitor.enabled
                or dscfg.wandb.enabled or dscfg.comet.enabled):
            from deepspeed_tpu.monitor.monitor import MonitorMaster
            self.monitor = MonitorMaster(dscfg)
        from deepspeed_tpu.runtime.pipe.schedule import (
            bubble_fraction, lockstep_bubble_fraction)
        log_dist(
            f"pipeline engine: {self.num_stages} stages x "
            f"{self.micro_batches} microbatches (lockstep bubble "
            f"{lockstep_bubble_fraction(self.micro_batches, self.num_stages):.2f}"
            f", host-1F1B model "
            f"{bubble_fraction(self.micro_batches, self.num_stages):.2f})",
            ranks=[0])

    # ------------------------------------------------------------------
    def _build_step(self):
        mod = self.module
        tx = self.tx
        clip = self.clip
        mesh = self.mesh
        stages = self.num_stages

        def step(staged, tied, opt_state, toks_mb):
            # executor expects [L, ...] stacking; re-fold the stage dim
            flat = unstack_stages(staged) if stages > 1 else staged
            loss, g_staged, g_tied = pipeline_train_step_1f1b(
                mod.block_fn, flat, tied, toks_mb, mod.first_fn, mod.last_fn,
                mesh=mesh)
            if stages > 1:
                g_staged = jax.tree.map(
                    lambda g, p: g.reshape(p.shape), g_staged, staged)
            grads = (g_staged, g_tied)
            if clip:
                gnorm = optax.global_norm(grads)
                scale = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * scale, grads)
            updates, new_opt = tx.update(grads, opt_state, (staged, tied))
            new_staged, new_tied = optax.apply_updates((staged, tied), updates)
            return new_staged, new_tied, new_opt, loss

        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2))

    def eval_batch(self, tokens) -> float:
        """Forward-only pipelined loss (reference PipelineEngine.eval_batch,
        engine.py:405 — the InferenceSchedule fill-drain executor)."""
        from deepspeed_tpu.runtime.pipe.one_f_one_b import pipeline_eval_step
        tokens = np.asarray(tokens)
        b, s = tokens.shape
        m = self.micro_batches
        if b % m:
            raise ValueError(f"batch {b} not divisible by micro_batches {m}")
        toks_mb = jnp.asarray(tokens.reshape(m, b // m, s), jnp.int32)
        if self._eval_fn is None:
            mod, mesh, stages = self.module, self.mesh, self.num_stages

            def ev(staged, tied, toks):
                flat = unstack_stages(staged) if stages > 1 else staged
                return pipeline_eval_step(mod.block_fn, flat, tied, toks,
                                          mod.first_fn, mod.last_fn,
                                          mesh=mesh)
            self._eval_fn = jax.jit(ev)
        return float(self._eval_fn(self.staged_params, self.tied_params,
                                   toks_mb))

    def save_checkpoint(self, save_dir: str, tag=None) -> str:
        """Orbax checkpoint of the stage-sharded state, committed with the
        same ``latest``-tag protocol as the main engine (checkpoint/
        engine.py: the tag file is the durability marker, written strictly
        after the array write)."""
        import orbax.checkpoint as ocp

        from deepspeed_tpu.checkpoint.engine import (_ckpt_dir,
                                                     _commit_latest,
                                                     write_manifest)
        tag = tag if tag is not None else f"global_step{self.global_steps}"
        path = _ckpt_dir(save_dir, tag)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(
            path, {"staged": self.staged_params, "tied": self.tied_params,
                   "opt_state": self.opt_state,
                   "scalars": {"global_steps": jnp.int32(self.global_steps)}},
            force=True)
        # synchronous contract: orbax saves async under the hood; finish
        # before the latest-tag commit so a crash can't publish a partial
        ckptr.wait_until_finished()
        ckptr.close()
        if jax.process_index() == 0:
            # same committed-checkpoint contract as the main engine: the
            # commit-detection tooling (is_committed / dstpu_report --ckpt /
            # resume discovery) keys on ds_meta.json + the manifest, so a
            # pipeline checkpoint must carry them too or it reads as torn
            import json as _json
            with open(os.path.join(path, "ds_meta.json"), "w") as f:
                _json.dump({"global_steps": self.global_steps}, f)
                f.flush()
                os.fsync(f.fileno())
            write_manifest(path, extra_meta={
                "tag": tag, "global_steps": self.global_steps})
            # atomic tmp+fsync+rename commit (same crash-safety contract as
            # the main engine's checkpoint path)
            _commit_latest(save_dir, tag)
        return path

    def load_checkpoint(self, load_dir: str, tag=None) -> str:
        import orbax.checkpoint as ocp

        from deepspeed_tpu.checkpoint.engine import LATEST_FILE, _ckpt_dir
        root = os.path.abspath(load_dir)
        if tag is None:
            latest = os.path.join(root, LATEST_FILE)
            if not os.path.exists(latest):
                raise FileNotFoundError(
                    f"no '{LATEST_FILE}' tag file under {root}")
            with open(latest) as f:
                tag = f.read().strip()
        path = _ckpt_dir(root, tag)
        tmpl = {"staged": self.staged_params, "tied": self.tied_params,
                "opt_state": self.opt_state,
                "scalars": {"global_steps": jnp.int32(self.global_steps)}}
        ckptr = ocp.StandardCheckpointer()
        try:
            restored = ckptr.restore(
                path, jax.tree.map(ocp.utils.to_shape_dtype_struct, tmpl))
        finally:
            ckptr.close()
        self.staged_params = jax.device_put(restored["staged"],
                                            self._staged_spec)
        self.tied_params = restored["tied"]
        self.opt_state = restored["opt_state"]
        self.global_steps = int(restored["scalars"]["global_steps"])
        return path

    def consolidated_module_params(self):
        """(stacked [L, ...], tied) with the stage dim folded away — the
        layout model adapters split from (e.g. ``llama_params_from_pipe``
        rebuilds a dense model tree for cross-topology restore)."""
        # replicate before the host copy: on multi-host meshes the staged
        # leaves span non-addressable devices and np.asarray would raise
        rep = jax.tree.map(
            lambda _: NamedSharding(self.mesh, P()), self.staged_params)
        gathered = jax.jit(lambda t: t, out_shardings=rep)(self.staged_params)
        host = jax.tree.map(np.asarray, gathered)
        stacked = unstack_stages(host) if self.num_stages > 1 else host
        return stacked, jax.tree.map(np.asarray, self.tied_params)

    def train_batch(self, tokens) -> float:
        """tokens: [B, S] int32 with B divisible by micro_batches (reference
        train_batch consumes micro_batches x micro_batch_size samples)."""
        tokens = np.asarray(tokens)
        b, s = tokens.shape
        m = self.micro_batches
        if b % m:
            raise ValueError(f"batch {b} not divisible by micro_batches {m}")
        toks_mb = jnp.asarray(tokens.reshape(m, b // m, s), jnp.int32)
        if self._step_fn is None:
            self._build_step()
        self.tput_timer.batch_size = b        # actual batch, not config guess
        self.tput_timer.start()
        self.staged_params, self.tied_params, self.opt_state, loss = \
            self._step_fn(self.staged_params, self.tied_params,
                          self.opt_state, toks_mb)
        loss = float(loss)
        self.tput_timer.stop(global_step=True)
        self.global_steps += 1
        self.global_samples += b
        if (self.monitor is not None and self.steps_per_print
                and self.global_steps % self.steps_per_print == 0):
            # same cadence + cumulative-samples x-axis as the main engine's
            # _record_metrics
            self.monitor.write_events(
                [("Train/Samples/train_loss", loss, self.global_samples)])
        return loss
