"""Pipeline parallelism (reference: deepspeed/runtime/pipe/)."""
from deepspeed_tpu.runtime.pipe.engine import PipeModule, PipelineEngine  # noqa: F401
from deepspeed_tpu.runtime.pipe.module import (                           # noqa: F401
    partition_balanced, partition_uniform)
from deepspeed_tpu.runtime.pipe.one_f_one_b import (                      # noqa: F401
    pipeline_train_step_1f1b)
from deepspeed_tpu.runtime.pipe.schedule import (                         # noqa: F401
    InferenceSchedule, TrainSchedule, bubble_fraction)
from deepspeed_tpu.runtime.pipe.spmd import pipeline_apply, stack_to_stages  # noqa: F401
