"""Pipeline instruction schedules.

Reference analog: ``deepspeed/runtime/pipe/schedule.py`` — the instruction set
(:327-475: LoadMicroBatch, ForwardPass, BackwardPass, SendActivation,
RecvActivation, SendGrad, RecvGrad, ReduceGrads, ReduceTiedGrads, OptimizerStep)
and the 1F1B ``TrainSchedule`` (:189) / ``InferenceSchedule`` (:135) generators.

On TPU the *executor* is SPMD (see ``spmd.py``): XLA schedules sends/recvs as
``ppermute`` collectives inside one compiled program, and autodiff derives the
backward pipeline. The instruction streams remain useful as (a) the analytical
model of the schedule (bubble accounting, tests), (b) the contract for a future
host-driven multi-slice executor over DCN. Generators are pure and unit-tested.
"""

import dataclasses
from typing import Iterator, List


@dataclasses.dataclass(frozen=True)
class PipeInstruction:
    micro_batch_id: int = -1

    def __repr__(self):
        mb = f"(mb={self.micro_batch_id})" if self.micro_batch_id >= 0 else ""
        return f"{type(self).__name__}{mb}"


class LoadMicroBatch(PipeInstruction): pass
class ForwardPass(PipeInstruction): pass
class BackwardPass(PipeInstruction): pass
class SendActivation(PipeInstruction): pass
class RecvActivation(PipeInstruction): pass
class SendGrad(PipeInstruction): pass
class RecvGrad(PipeInstruction): pass
class ReduceGrads(PipeInstruction): pass
class ReduceTiedGrads(PipeInstruction): pass
class OptimizerStep(PipeInstruction): pass


class PipeSchedule:
    """Base generator (reference: schedule.py:9 PipeSchedule)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()

    def num_pipe_buffers(self) -> int:
        return 2


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference: schedule.py:135)."""

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for t in range(total):
            cmds: List[PipeInstruction] = []
            mb = t - self.stage_id
            if 0 <= mb < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(mb))
                else:
                    cmds.append(RecvActivation(mb))
                cmds.append(ForwardPass(mb))
                if not self.is_last_stage:
                    cmds.append(SendActivation(mb))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B (reference: schedule.py:189): warmup forwards, steady-state alternating
    fwd/bwd, cooldown backwards, then grad reduce + optimizer step."""

    def num_pipe_buffers(self) -> int:
        # reference :268 — buffers needed = min(stages - stage_id, micro_batches)
        return max(2, min(self.stages - self.stage_id, self.micro_batches))

    def steps(self):
        m, s, p = self.micro_batches, self.stages, self.stage_id
        warmup = min(s - p - 1, m)
        remaining = m - warmup
        fwd_mb = 0
        bwd_mb = 0

        # warmup: forwards only
        for _ in range(warmup):
            cmds: List[PipeInstruction] = []
            cmds.append(LoadMicroBatch(fwd_mb) if p == 0 else RecvActivation(fwd_mb))
            cmds.append(ForwardPass(fwd_mb))
            if p != s - 1:
                cmds.append(SendActivation(fwd_mb))
            yield cmds
            fwd_mb += 1

        # steady state: 1F1B
        for i in range(remaining):
            cmds = []
            cmds.append(LoadMicroBatch(fwd_mb) if p == 0 else RecvActivation(fwd_mb))
            cmds.append(ForwardPass(fwd_mb))
            if p != s - 1:
                cmds.append(SendActivation(fwd_mb))
            fwd_mb += 1
            if p != s - 1:
                cmds.append(RecvGrad(bwd_mb))
            cmds.append(BackwardPass(bwd_mb))
            if p != 0:
                cmds.append(SendGrad(bwd_mb))
            yield cmds
            bwd_mb += 1

        # cooldown: backwards only
        while bwd_mb < m:
            cmds = []
            if p != s - 1:
                cmds.append(RecvGrad(bwd_mb))
            cmds.append(BackwardPass(bwd_mb))
            if p != 0:
                cmds.append(SendGrad(bwd_mb))
            yield cmds
            bwd_mb += 1

        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class LockstepSPMDSchedule(PipeSchedule):
    """The timeline the SPMD 1F1B executor (``one_f_one_b.py``) actually
    runs — and the module that DRIVES it: the executor derives its macro-step
    count from this stream (``num_macro_steps``), and its in-scan fwd/bwd
    occupancy masks are tested equal to the stream's
    ForwardPass/BackwardPass instructions (test_pipeline.py).

    Every stage steps in lockstep inside one compiled scan: macro-step ``t``
    forwards microbatch ``t - stage`` and backwards microbatch
    ``t - (2(S-1) - stage)``. Fill+drain spans ``2(S-1)`` macro-steps, but
    the executor predicates each half with ``lax.cond`` so an inactive
    forward/backward is skipped at runtime — wall-clock cost is the true
    1F1B ``(S-1)(F+B)`` fill+drain (``bubble_fraction``), not the all-masked
    ``2(S-1)(F+B)`` (``lockstep_bubble_fraction``, kept as the
    no-predication comparison model)."""

    def num_pipe_buffers(self) -> int:
        # ring buffer of stage inputs held for recompute-backward
        return min(self.micro_batches, 2 * self.stages - 1)

    def steps(self):
        m, s, p = self.micro_batches, self.stages, self.stage_id
        for t in range(2 * (s - 1) + m):
            cmds: List[PipeInstruction] = []
            f = t - p
            if 0 <= f < m:
                cmds.append(LoadMicroBatch(f) if p == 0 else RecvActivation(f))
                cmds.append(ForwardPass(f))
                if p != s - 1:
                    cmds.append(SendActivation(f))
            b = t - (2 * (s - 1) - p)
            if 0 <= b < m:
                if p != s - 1:
                    cmds.append(RecvGrad(b))
                cmds.append(BackwardPass(b))
                if p != 0:
                    cmds.append(SendGrad(b))
            yield cmds
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


def num_macro_steps(micro_batches: int, stages: int) -> int:
    """Macro-step count of the lockstep SPMD executor, derived from the
    instruction stream (the final reduce/step tail is outside the scan)."""
    return sum(1 for _ in LockstepSPMDSchedule(
        micro_batches, stages, 0).steps()) - 1


def bubble_fraction(micro_batches: int, stages: int) -> float:
    """Pipeline bubble overhead of GPipe/1F1B: (s-1)/(m+s-1)."""
    return (stages - 1) / (micro_batches + stages - 1)


def lockstep_bubble_fraction(micro_batches: int, stages: int) -> float:
    """Bubble of a *non-predicated* lockstep executor: every macro-step costs
    one full stage fwd+bwd on every device (fill/drain steps run masked dead
    compute), so overhead = 2(s-1) dead macro-steps out of 2(s-1)+m. The
    shipping executor predicates fill/drain halves with ``lax.cond`` and pays
    ``bubble_fraction`` instead; this model is kept as the comparison
    baseline for ``dstpu_pipe_bench``."""
    t = num_macro_steps(micro_batches, stages)
    return (t - micro_batches) / t
