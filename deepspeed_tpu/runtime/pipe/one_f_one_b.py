"""1F1B SPMD pipeline executor — bounded-memory training pipeline in one jit.

Reference analog: ``TrainSchedule`` 1F1B (``runtime/pipe/schedule.py:189``),
``PipelineEngine._exec_schedule`` (``engine.py:1408``), tied weights
(``module.py:77 TiedLayerSpec``, ``engine.py:275 _exec_reduce_tied_grads``).

TPU redesign: the reference drives a host-side instruction loop with p2p
send/recvs; here the whole schedule is ONE ``lax.scan`` over global macro-steps
inside a ``shard_map`` over the ``pipe`` axis. Each macro-step, every stage

- **forwards** microbatch ``f = t - stage`` (activation arriving by
  ``ppermute``; stage 0 embeds tokens via ``first_fn``), saving only its
  *stage-input* activation in a ring buffer, and
- **backwards** microbatch ``b = t - (2(S-1) - stage)`` by recomputing the
  stage forward from the saved input under ``jax.vjp`` (per-stage activation
  checkpointing) and pushing ``dx`` to the previous stage with a reverse
  ``ppermute``. The last stage seeds the backward from the loss gradient
  (``last_fn``) of the microbatch it forwarded in the same macro-step.

The defining 1F1B property — activation memory bounded by the pipeline depth,
not the microbatch count — holds: the ring buffer keeps at most
``min(M, 2(S - stage) - 1)`` stage inputs (the reference's alternating-slot
schedule keeps ``S - stage``; the macro-step formulation pays ≤2x that bound in
exchange for running fill+drain in ``2(S-1) + M`` fully-compiled steps).

**Bubble = true 1F1B ``(S-1)/(M+S-1)``** (``schedule.bubble_fraction``): the
forward and backward halves of each macro-step are predicated with
``lax.cond`` on their occupancy masks, so a stage whose forward (or backward)
is inactive this macro-step SKIPS that compute at runtime — HLO conditionals
branch per-device, and the ``ppermute`` handoffs stay outside the conds so
the SPMD collective schedule is uniform. Per-step wall-clock is the max over
stages of *active* work: the first ``S-1`` macro-steps cost a forward only,
the last ``S-1`` a backward only, and the ``M`` in between cost fwd+bwd —
total ``(M+S-1)(F+B)`` against ideal ``M(F+B)``, i.e. the reference
``TrainSchedule``'s bubble exactly (the earlier all-masked formulation paid
``2(S-1)/(2(S-1)+M)``, ``schedule.lockstep_bubble_fraction``, kept for
comparison; measured by ``bin/dstpu_pipe_bench``).

Tied weights (embedding used by ``first_fn`` at stage 0 and ``last_fn`` at the
last stage) are replicated across ``pipe``; their gradients from both ends are
``psum``-reduced over the axis — ReduceTiedGrads.

Inputs are **token ids**, not activations: stage 0 embeds inside the pipeline,
so microbatches replicate as [M, B, S] int32 — the O(M·B·S·D) activation
replication of the GPipe executor's input never materializes.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.runtime.pipe.spmd import stack_to_stages


def _cond(pred, true_fn, false_fn, operand, predicate: bool):
    """``lax.cond`` when ``predicate`` (runtime branch: inactive halves are
    skipped — true 1F1B cost), else compute-both-and-mask (the all-masked
    lockstep executor, kept as the A/B baseline for ``dstpu_pipe_bench``)."""
    if predicate:
        return jax.lax.cond(pred, true_fn, false_fn, operand)
    tv, fv = true_fn(operand), false_fn(operand)
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), tv, fv)


def pipeline_train_step_1f1b(block_fn: Callable, stacked_params: Any,
                             tied_params: Any, tokens_mb,
                             first_fn: Callable, last_fn: Callable,
                             mesh=None, predicate: bool = True):
    """One pipelined forward+backward over all microbatches.

    block_fn(layer_params, x) -> x            — one transformer layer
    stacked_params: leaves [L, ...]           — layer-stacked (flax scan layout)
    tied_params: pytree                       — replicated across stages
                                                (embedding/unembed, tied)
    tokens_mb: [M, B, S] int32                — microbatched token ids
    first_fn(tied, tokens) -> x [B, S, D]     — stage-0 input embedding
    last_fn(tied, x, tokens) -> scalar loss   — last-stage head + loss
    predicate                                 — skip inactive fwd/bwd halves
                                                at runtime (False = masked
                                                dead compute, bench baseline)

    Returns (mean_loss, grads_stacked [P, L/P, ...] sharded over ``pipe``,
    grads_tied replicated). Gradients are averaged over microbatches.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    s = mesh.shape["pipe"]
    m = tokens_mb.shape[0]
    if s == 1:
        return _no_pipe(block_fn, stacked_params, tied_params, tokens_mb,
                        first_fn, last_fn)

    staged = stack_to_stages(stacked_params, s)
    param_specs = jax.tree.map(lambda x: P("pipe", *([None] * (x.ndim - 1))),
                               staged)
    # the schedule module drives the executor: macro-step count and ring
    # depth come from the lockstep instruction stream; the in-scan fwd/bwd
    # masks below implement exactly its ForwardPass/BackwardPass occupancy
    # (asserted equal in test_pipeline.py::test_lockstep_masks_match_schedule)
    from deepspeed_tpu.runtime.pipe.schedule import (LockstepSPMDSchedule,
                                                     num_macro_steps)
    bufs = LockstepSPMDSchedule(m, s, 0).num_pipe_buffers()
    total_steps = num_macro_steps(m, s)

    def body(local_params, tied, toks):
        local_params = jax.tree.map(lambda x: x[0], local_params)
        p = jax.lax.axis_index("pipe")

        def apply_stage(lp, x):
            def layer(carry, layer_p):
                return block_fn(layer_p, carry), None
            y, _ = jax.lax.scan(layer, x, lp)
            return y

        x_shape = jax.eval_shape(lambda td, t: first_fn(td, t), tied,
                                 toks[0]).shape
        x_dtype = jax.eval_shape(lambda td, t: first_fn(td, t), tied,
                                 toks[0]).dtype

        fwd_perm = [(i, (i + 1) % s) for i in range(s)]
        bwd_perm = [(i, (i - 1) % s) for i in range(s)]

        def step(carry, t):
            cur_fwd, cur_bwd, buf, gp_acc, gt_acc, loss_acc = carry

            # ---------------- forward: mb f = t - p -----------------------
            # predicated: fill/drain steps where this stage has no forward
            # branch to the skip side at runtime (cost F only during fill)
            f = t - p
            fwd_active = jnp.logical_and(f >= 0, f < m)
            f_clip = jnp.clip(f, 0, m - 1)
            tok_f = jax.lax.dynamic_index_in_dim(toks, f_clip, 0,
                                                 keepdims=False)

            def do_fwd(buf):
                x_in = jnp.where(p == 0, first_fn(tied, tok_f), cur_fwd)
                buf = jax.lax.dynamic_update_index_in_dim(
                    buf, x_in, f_clip % bufs, 0)
                return apply_stage(local_params, x_in), buf

            y, buf = _cond(
                fwd_active, do_fwd,
                lambda buf: (jnp.zeros(x_shape, x_dtype), buf), buf,
                predicate)

            # ---------------- backward: mb b = t - (2(S-1) - p) -----------
            b = t - (2 * (s - 1) - p)
            bwd_active = jnp.logical_and(b >= 0, b < m)
            b_clip = jnp.clip(b, 0, m - 1)
            tok_b = jax.lax.dynamic_index_in_dim(toks, b_clip, 0,
                                                 keepdims=False)

            def do_bwd(accs):
                gp_acc, gt_acc, loss_acc = accs
                # for the last stage, buf was written THIS step (f == b there)
                x_saved = jax.lax.dynamic_index_in_dim(buf, b_clip % bufs, 0,
                                                       keepdims=False)
                y_b, vjp = jax.vjp(apply_stage, local_params, x_saved)

                # last stage seeds from the loss of the mb it forwarded this
                # step (head + loss + unembed-side tied grads, skipped on all
                # other stages)
                def seed_from_loss(args):
                    gt_acc, loss_acc = args
                    loss_b, (g_loss, dtied_last) = jax.value_and_grad(
                        lambda yy, td: last_fn(td, yy, tok_b),
                        argnums=(0, 1))(y_b, tied)
                    gt_acc = jax.tree.map(
                        lambda a, g: a + g.astype(a.dtype), gt_acc,
                        dtied_last)
                    return g_loss, gt_acc, loss_acc + loss_b

                g_in, gt_acc, loss_acc = _cond(
                    p == s - 1, seed_from_loss,
                    lambda args: (cur_bwd, *args), (gt_acc, loss_acc),
                    predicate)
                dparams, dx = vjp(g_in)
                gp_acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                                      gp_acc, dparams)

                # embedding side (stage 0 only): pull dx through first_fn
                def embed_grads(gt_acc):
                    _, vjp_first = jax.vjp(lambda td: first_fn(td, tok_b),
                                           tied)
                    (dtied_first,) = vjp_first(dx)
                    return jax.tree.map(
                        lambda a, g: a + g.astype(a.dtype), gt_acc,
                        dtied_first)

                gt_acc = _cond(p == 0, embed_grads, lambda a: a, gt_acc,
                               predicate)
                return dx, gp_acc, gt_acc, loss_acc

            dx, gp_acc, gt_acc, loss_acc = _cond(
                bwd_active, do_bwd,
                lambda accs: (jnp.zeros(x_shape, x_dtype), *accs),
                (gp_acc, gt_acc, loss_acc), predicate)

            # ---------------- stage handoffs ------------------------------
            # uniform across devices every step (outside the conds)
            nxt_fwd = jax.lax.ppermute(y, "pipe", fwd_perm)
            nxt_bwd = jax.lax.ppermute(dx, "pipe", bwd_perm)
            return (nxt_fwd, nxt_bwd, buf, gp_acc, gt_acc, loss_acc), None

        zeros_x = jnp.zeros(x_shape, x_dtype)
        carry0 = (
            zeros_x,
            zeros_x,
            jnp.zeros((bufs, *x_shape), x_dtype),
            jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                         local_params),
            jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), tied),
            jnp.float32(0.0),
        )
        (_, _, _, gp, gt, loss_sum), _ = jax.lax.scan(
            step, carry0, jnp.arange(total_steps))

        # ReduceTiedGrads + loss broadcast (only contributing stages are
        # nonzero, so a plain psum over pipe is the tied-group allreduce)
        gt = jax.tree.map(lambda g: jax.lax.psum(g, "pipe") / m, gt)
        loss = jax.lax.psum(loss_sum, "pipe") / m
        gp = jax.tree.map(lambda g: (g / m)[None], gp)   # restage [1, L/P,...]
        return loss, gp, gt

    loss, gp, gt = jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=(P(), param_specs, P()),
        check_vma=False)(staged, tied_params, tokens_mb)
    return loss, gp, gt


def pipeline_eval_step(block_fn: Callable, stacked_params: Any,
                       tied_params: Any, tokens_mb, first_fn: Callable,
                       last_fn: Callable, mesh=None):
    """Forward-only fill-drain pipeline (the ``InferenceSchedule`` executor —
    reference ``PipelineEngine.eval_batch``, engine.py:405, driving
    schedule.py:135). Same lockstep formulation as the 1F1B executor minus
    the backward: ``m + s - 1`` macro-steps, derived from the
    InferenceSchedule instruction stream. Returns the mean loss."""
    mesh = mesh or mesh_lib.get_global_mesh()
    s = mesh.shape["pipe"]
    m = tokens_mb.shape[0]
    if s == 1:
        return jnp.mean(jax.vmap(
            lambda toks: _forward_one_mb(block_fn, stacked_params,
                                         tied_params, toks, first_fn,
                                         last_fn))(tokens_mb))

    from deepspeed_tpu.runtime.pipe.schedule import InferenceSchedule
    total_steps = sum(1 for _ in InferenceSchedule(m, s, 0).steps())

    staged = stack_to_stages(stacked_params, s)
    param_specs = jax.tree.map(lambda x: P("pipe", *([None] * (x.ndim - 1))),
                               staged)

    def body(local_params, tied, toks):
        local_params = jax.tree.map(lambda x: x[0], local_params)
        p = jax.lax.axis_index("pipe")

        def apply_stage(x):
            def layer(carry, lp):
                return block_fn(lp, carry), None
            y, _ = jax.lax.scan(layer, x, local_params)
            return y

        x_shape = jax.eval_shape(lambda td, t: first_fn(td, t), tied,
                                 toks[0])
        fwd_perm = [(i, (i + 1) % s) for i in range(s)]

        def step(carry, t):
            cur, loss_acc = carry
            f = t - p
            active = jnp.logical_and(f >= 0, f < m)
            f_clip = jnp.clip(f, 0, m - 1)
            tok_f = jax.lax.dynamic_index_in_dim(toks, f_clip, 0,
                                                 keepdims=False)

            def do_fwd(loss_acc):
                x_in = jnp.where(p == 0, first_fn(tied, tok_f), cur)
                y = apply_stage(x_in)
                # head + loss only on the last stage (skipped elsewhere)
                loss_acc = jax.lax.cond(
                    p == s - 1,
                    lambda la: la + last_fn(tied, y, tok_f).astype(la.dtype),
                    lambda la: la, loss_acc)
                return y, loss_acc

            y, loss_acc = jax.lax.cond(
                active, do_fwd,
                lambda la: (jnp.zeros(x_shape.shape, x_shape.dtype), la),
                loss_acc)
            return (jax.lax.ppermute(y, "pipe", fwd_perm), loss_acc), None

        zeros_x = jnp.zeros(x_shape.shape, x_shape.dtype)
        (_, loss_sum), _ = jax.lax.scan(
            step, (zeros_x, jnp.float32(0.0)), jnp.arange(total_steps))
        return jax.lax.psum(loss_sum, "pipe") / m

    return jax.shard_map(
        body, mesh=mesh, in_specs=(param_specs, P(), P()),
        out_specs=P(), check_vma=False)(staged, tied_params, tokens_mb)


def _forward_one_mb(block_fn, stacked_params, tied_params, toks, first_fn,
                    last_fn):
    """Unpipelined forward of one microbatch: the single source of the
    embed -> layer-scan -> head/loss contract shared by the eval executor's
    s==1 path and the _no_pipe training oracle."""
    x = first_fn(tied_params, toks)

    def layer(carry, lp):
        return block_fn(lp, carry), None
    y, _ = jax.lax.scan(layer, x, stacked_params)
    return last_fn(tied_params, y, toks)


def _no_pipe(block_fn, stacked_params, tied_params, tokens_mb, first_fn,
             last_fn):
    """Single-stage reference semantics (also the parity oracle in tests)."""
    def loss_fn(sp, tp):
        return jnp.mean(jax.vmap(
            lambda toks: _forward_one_mb(block_fn, sp, tp, toks, first_fn,
                                         last_fn))(tokens_mb))

    (loss), (gp, gt) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        stacked_params, tied_params)
    return loss, gp, gt
