"""SPMD pipeline executor — GPipe over the ``pipe`` mesh axis inside one jit.

NOTE: for TRAINING use ``one_f_one_b.pipeline_train_step_1f1b`` (driven by
``pipe/engine.PipelineEngine``) — it bounds activation memory by pipeline depth
and takes token inputs, avoiding this executor's replicated [M, B, S, D]
activation input. This GPipe rotation remains the forward/inference pipeline
and the autodiff-through-scan baseline.

Reference analog: ``PipelineEngine._exec_schedule`` (``runtime/pipe/engine.py:1408``)
+ p2p send/recv (``runtime/pipe/p2p.py``). TPU redesign (SURVEY.md §7 hard-part 2):
instead of a host-driven instruction loop with point-to-point sends, the whole
fill-process-drain rotation is a ``lax.scan`` whose per-step stage handoff is a
``ppermute`` — one compiled program per train step. ``jax.grad`` through the scan
derives the backward pipeline (reverse ppermutes = SendGrad/RecvGrad) mechanically,
which is why no BackwardPass instruction executor exists here.

Layout: per-layer params are stacked on a leading layer dim [L, ...], reshaped to
[P, L/P, ...] and sharded over ``pipe``; each stage scans its local L/P layers.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib


def stack_to_stages(stacked_params: Any, num_stages: int) -> Any:
    """[L, ...] -> [P, L/P, ...] per leaf (layer-uniform partitioning, the
    reference's ``partition_method='uniform'``; see module.py for 'parameters')."""
    def reshape(x):
        l = x.shape[0]
        assert l % num_stages == 0, f"{l} layers not divisible by {num_stages} stages"
        return x.reshape(num_stages, l // num_stages, *x.shape[1:])
    return jax.tree.map(reshape, stacked_params)


def unstack_stages(staged_params: Any) -> Any:
    """Inverse of :func:`stack_to_stages`: [P, L/P, ...] -> [L, ...] per
    leaf. The single source of the stage-refold used by the pipeline
    engine's step/eval builders and checkpoint consolidation — any change
    to the stage partitioning layout must update both functions together."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
        staged_params)


def pipeline_apply(block_fn: Callable, stacked_params: Any, x_microbatches,
                   mesh=None, extra_args: tuple = ()):
    """Run microbatched activations through a layer pipeline.

    block_fn(layer_params, x, *extra_args) -> x  — one transformer block.
    stacked_params: leaves [L, ...] (flax nn.scan layout).
    x_microbatches: [M, B, S, D] activations (replicated across pipe).
    Returns [M, B, S, D] outputs (replicated).
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    n_stages = mesh.shape["pipe"]
    if n_stages == 1:
        def no_pipe(x):
            def layer_step(carry, lp):
                return block_fn(lp, carry, *extra_args), None
            y, _ = jax.lax.scan(layer_step, x, stacked_params)
            return y
        return jax.vmap(no_pipe)(x_microbatches) if x_microbatches.ndim > 3 \
            else no_pipe(x_microbatches)

    staged = stack_to_stages(stacked_params, n_stages)
    m = x_microbatches.shape[0]

    param_specs = jax.tree.map(lambda x: P("pipe", *([None] * (x.ndim - 1))), staged)
    x_spec = P()  # microbatches replicated into the pipe shard_map

    def body(local_params, x_mb):
        # local_params leaves: [1, L/P, ...] (shard of the stage dim) -> squeeze
        local_params = jax.tree.map(lambda x: x[0], local_params)
        p = jax.lax.axis_index("pipe")
        total_steps = m + n_stages - 1

        def apply_stage(x):
            def layer_step(carry, lp):
                return block_fn(lp, carry, *extra_args), None
            y, _ = jax.lax.scan(layer_step, x, local_params)
            return y

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t):
            cur, outputs = carry
            # stage 0 loads microbatch t (clipped reload after M is dead compute)
            inp = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            cur = jnp.where(p == 0, inp, cur)
            out = apply_stage(cur)
            # last stage stores microbatch t-(P-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            write = jnp.logical_and(p == n_stages - 1, t >= n_stages - 1)
            prev = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, out, prev), out_idx, 0)
            nxt = jax.lax.ppermute(out, "pipe", perm)
            return (nxt, outputs), None

        cur0 = jnp.zeros_like(x_mb[0])
        outs0 = jnp.zeros_like(x_mb)
        (_, outputs), _ = jax.lax.scan(step, (cur0, outs0), jnp.arange(total_steps))
        # replicate the last stage's outputs to every stage
        outputs = jax.lax.psum(
            jnp.where(p == n_stages - 1, outputs, jnp.zeros_like(outputs)), "pipe")
        return outputs

    return jax.shard_map(body, mesh=mesh, in_specs=(param_specs, x_spec),
                         out_specs=P(), check_vma=False)(staged, x_microbatches)
