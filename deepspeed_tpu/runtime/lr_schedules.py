"""Learning-rate schedules.

Reference analog: ``deepspeed/runtime/lr_schedules.py`` — ``LRRangeTest`` (:273),
``OneCycle`` (:371), ``WarmupLR`` (:633), ``WarmupDecayLR`` (:723),
``WarmupCosineLR`` (:774). Implemented as optax-compatible schedules
(``step -> lr``), selected by the same config ``scheduler.type`` strings.
"""

import math
from typing import Any, Callable, Dict

import jax.numpy as jnp

Schedule = Callable[[Any], Any]


def _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type="log"):
    warmup_num_steps = max(warmup_num_steps, 1)
    frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
    if warmup_type == "log":
        # reference WarmupLR: inverse_log_warm_up * log(step + 1)
        frac = jnp.log1p(frac * (math.e - 1.0))
    return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    """reference: WarmupLR lr_schedules.py:633 — warmup then hold."""
    def fn(step):
        return jnp.where(step < warmup_num_steps,
                         _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                                 warmup_type),
                         warmup_max_lr)
    return fn


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    """reference: WarmupDecayLR lr_schedules.py:723 — warmup then linear decay to 0."""
    def fn(step):
        w = _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
        decay_frac = jnp.clip(
            (total_num_steps - step) / max(total_num_steps - warmup_num_steps, 1), 0.0, 1.0)
        return jnp.where(step < warmup_num_steps, w, warmup_max_lr * decay_frac)
    return fn


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                     lr: float = 0.001, **_) -> Schedule:
    """reference: WarmupCosineLR lr_schedules.py:774 (ratio-based)."""
    def fn(step):
        warm_ratio = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.clip(
            step / max(warmup_num_steps, 1), 0.0, 1.0)
        progress = jnp.clip((step - warmup_num_steps) /
                            max(total_num_steps - warmup_num_steps, 1), 0.0, 1.0)
        cos_ratio = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress))
        return lr * jnp.where(step < warmup_num_steps, warm_ratio, cos_ratio)
    return fn


def one_cycle(cycle_min_lr: float, cycle_max_lr: float, cycle_first_step_size: int = 2000,
              cycle_second_step_size: int = None, decay_step_size: int = 0,
              decay_lr_rate: float = 0.0, **_) -> Schedule:
    """reference: OneCycle lr_schedules.py:371 (lr triangle then decay; momentum cycle
    is handled by the optimizer wrapper when enabled)."""
    second = cycle_second_step_size if cycle_second_step_size is not None \
        else cycle_first_step_size

    def fn(step):
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.clip(
            step / max(cycle_first_step_size, 1), 0.0, 1.0)
        down_progress = jnp.clip((step - cycle_first_step_size) / max(second, 1), 0.0, 1.0)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_progress
        end_of_cycle = cycle_first_step_size + second
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - end_of_cycle, 0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
        else:
            decayed = jnp.full_like(jnp.asarray(step, jnp.float32), cycle_min_lr)
        return jnp.where(step <= cycle_first_step_size, up,
                         jnp.where(step <= end_of_cycle, down, decayed))
    return fn


def lr_range_test(lr_range_test_min_lr: float = 0.001, lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    """reference: LRRangeTest lr_schedules.py:273 (continuous/staircase lr sweep)."""
    def fn(step):
        interval = step / max(lr_range_test_step_size, 1)
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)
    return fn


def constant_lr(lr: float = 0.001, **_) -> Schedule:
    return lambda step: jnp.full_like(jnp.asarray(step, jnp.float32), lr)


SCHEDULES: Dict[str, Callable[..., Schedule]] = {
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
    "OneCycle": one_cycle,
    "LRRangeTest": lr_range_test,
    "Constant": constant_lr,
}


def build_schedule(sched_type: str, params: Dict[str, Any]) -> Schedule:
    if sched_type not in SCHEDULES:
        raise ValueError(f"unknown scheduler '{sched_type}'; known: {list(SCHEDULES)}")
    return SCHEDULES[sched_type](**params)
