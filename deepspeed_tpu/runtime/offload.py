"""ZeRO-Offload / ZeRO-Infinity: host-DRAM and NVMe optimizer-state tiers.

Reference analogs:
- ZeRO-Offload: optimizer states + fp32 master params in host memory, CPU fused
  Adam update (``runtime/zero/offload_config.py``, ``ops/adam/cpu_adam.py``)
- ZeRO-Infinity: states on NVMe, swapped in/out per sub-group around the update
  (``runtime/swap_tensor/partitioned_optimizer_swapper.py:29`` and the
  double-buffered ``pipelined_optimizer_swapper.py``), over the aio engine

TPU-native shape: the device keeps compute-dtype (bf16) params and produces grads
under jit; the host keeps fp32 master params + optimizer moments as numpy arrays
and runs the fused C++ kernel (Adam/AdamW, Adagrad, or Lion — reference supports
exactly these CPU optimizers); updated masters stream back as a bf16 shadow
(half the H2D bytes). With NVMe enabled, moments live in per-leaf files;
sub-groups are prefetched with the async engine while the previous sub-group
updates (Infinity's pipelined swapper). Twin-Flow (``ratio`` < 1, reference
ZeRO-Offload++ engine.py:757) keeps the first ``1-ratio`` fraction of sub-groups
permanently in host RAM.
"""

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.config.config import OffloadConfig
from deepspeed_tpu.ops.async_io import AsyncIOHandle
from deepspeed_tpu.ops.cpu_adam import CPUAdagrad, CPUAdam, CPULion, to_bf16
from deepspeed_tpu.utils.logging import log_dist

# optimizer-type → host kernel (reference: cpu_adam/cpu_adagrad/cpu_lion builders)
_HOST_OPTIMIZERS = {
    "adam": CPUAdam, "adamw": CPUAdam, "cpu_adam": CPUAdam,
    "adagrad": CPUAdagrad, "cpu_adagrad": CPUAdagrad,
    "lion": CPULion, "cpu_lion": CPULion,
}


class _LeafState:
    """Host state for one parameter leaf: fp32 master + n_states moment
    buffers. On the nvme tier with ``swap_masters`` the master itself also
    lives in a file (full ZeRO-Infinity — reference swaps the flat fp32
    param shard too) and ``master`` is None while swapped out."""

    def __init__(self, idx: int, master: np.ndarray, n_states: int,
                 nvme_dir: Optional[str], swap_master: bool):
        self.idx = idx
        self.shape = master.shape
        self.size = master.size
        self.nvme = nvme_dir is not None
        self.master_path = None
        if self.nvme:
            self.paths = [os.path.join(nvme_dir, f"state{s}_{idx}.bin")
                          for s in range(n_states)]
            self.states: List[Optional[np.ndarray]] = [None] * n_states
            if swap_master:
                self.master_path = os.path.join(nvme_dir, f"master_{idx}.bin")
        else:
            self.states = [np.zeros_like(master) for _ in range(n_states)]
        self.master: Optional[np.ndarray] = master
        self._pending_drop = False


class UnsupportedOffloadOptimizer(ValueError):
    pass


class HostOffloadOptimizer:
    """Fused host optimizer over offloaded states, with optional NVMe swap.

    Single-controller / per-process shard semantics: each process updates the
    params it addresses (multi-host runs shard leaves over processes upstream).
    """

    def __init__(self, params_host: List[np.ndarray], opt_type: str,
                 opt_params: Dict[str, Any], offload: OffloadConfig,
                 sub_group_size: int = 4):
        key = (opt_type or "adamw").lower()
        if key not in _HOST_OPTIMIZERS:
            raise UnsupportedOffloadOptimizer(
                f"optimizer '{opt_type}' has no fused host kernel; offload "
                f"supports {sorted(set(_HOST_OPTIMIZERS))} (reference: CPU "
                "Adam/Adagrad/Lion only)")
        kernel_cls = _HOST_OPTIMIZERS[key]
        kwargs = dict(opt_params)
        kwargs.setdefault("adamw_mode", key != "adam")
        if "betas" in kwargs:
            kwargs["betas"] = tuple(kwargs["betas"])
        self.kernel = kernel_cls(**{k: v for k, v in kwargs.items()
                                    if k in ("lr", "betas", "eps", "weight_decay",
                                             "adamw_mode")})
        self.n_states = kernel_cls.num_states
        self.offload = offload
        nvme_dir = None
        if offload.device == "nvme":
            nvme_dir = os.path.join(offload.nvme_path or "/tmp/dstpu_nvme",
                                    f"proc{jax.process_index()}")
            os.makedirs(nvme_dir, exist_ok=True)
            self.aio = AsyncIOHandle(num_threads=offload.buffer_count * 2)
        self._swap_masters = bool(getattr(offload, "swap_masters", True))
        self.leaves = [
            # np.array(copy=True): device_get arrays can be read-only views
            _LeafState(i, np.array(p, dtype=np.float32, copy=True), self.n_states,
                       # Twin-Flow partial offload: first (1-ratio) leaves pinned in RAM
                       nvme_dir if (nvme_dir and i >= (1.0 - offload.ratio) *
                                    len(params_host)) else None,
                       swap_master=self._swap_masters)
            for i, p in enumerate(params_host)]
        if nvme_dir:
            # initialize moment (+ master) files; buffers must outlive the
            # async writes
            keepalive = []
            for leaf in self.leaves:
                if leaf.nvme:
                    zeros = np.zeros(leaf.shape, np.float32)
                    keepalive.append(zeros)
                    for path in leaf.paths:
                        self.aio.async_pwrite(zeros, path)
                    if leaf.master_path:
                        self.aio.async_pwrite(leaf.master, leaf.master_path)
            errors = self.aio.drain()
            if errors:
                raise RuntimeError(f"nvme state-file init failed ({errors} errors)")
            del keepalive
            for leaf in self.leaves:
                if leaf.master_path:
                    leaf.master = None        # authoritative copy is the file
        self.sub_group_size = max(1, sub_group_size)
        log_dist(f"host offload optimizer: kernel={kernel_cls.__name__} "
                 f"device={offload.device} leaves={len(self.leaves)} "
                 f"ratio={offload.ratio}", ranks=[0])

    # --- NVMe swap (reference: _prepare_sub_group / _release_sub_group) -----
    def _swap_in(self, group: List[_LeafState]) -> List[int]:
        reqs = []
        for leaf in group:
            if leaf.nvme and leaf.states[0] is None:
                for s in range(self.n_states):
                    leaf.states[s] = np.empty(leaf.shape, np.float32)
                    reqs.append(self.aio.async_pread(leaf.states[s], leaf.paths[s]))
            if leaf.master_path and leaf.master is None:
                leaf.master = np.empty(leaf.shape, np.float32)
                reqs.append(self.aio.async_pread(leaf.master, leaf.master_path))
        return reqs

    def _swap_out(self, group: List[_LeafState]):
        for leaf in group:
            if leaf.nvme:
                for s in range(self.n_states):
                    self.aio.async_pwrite(leaf.states[s], leaf.paths[s])
                if leaf.master_path:
                    self.aio.async_pwrite(leaf.master, leaf.master_path)
                # buffers dropped only after the writes drain WITHOUT error
                leaf._pending_drop = True

    def step(self, grads_host: List[np.ndarray], lr: Optional[float] = None):
        """One fused update over all leaves, sub-group pipelined when on NVMe
        (reference: pipelined_optimizer_swapper double buffering)."""
        groups = [self.leaves[i:i + self.sub_group_size]
                  for i in range(0, len(self.leaves), self.sub_group_size)]
        grad_groups = [grads_host[i:i + self.sub_group_size]
                       for i in range(0, len(grads_host), self.sub_group_size)]
        step_shared = self.kernel.step_count + 1

        pending: List[int] = self._swap_in(groups[0]) if groups else []
        for gi, (group, ggrads) in enumerate(zip(groups, grad_groups)):
            for r in pending:
                if self.aio.wait(r):
                    raise RuntimeError("nvme optimizer-state swap-in failed")
            # prefetch next sub-group while this one updates
            pending = self._swap_in(groups[gi + 1]) if gi + 1 < len(groups) else []
            for leaf, g in zip(group, ggrads):
                self.kernel.step_count = step_shared - 1
                self.kernel.step(leaf.master.ravel(),
                                 np.ascontiguousarray(g, np.float32).ravel(),
                                 *[s.ravel() for s in leaf.states], lr=lr)
            self._swap_out(group)
        if hasattr(self, "aio"):
            failures = self.aio.drain()
            if failures:
                # keep the in-RAM copies: the files may be truncated/stale
                for leaf in self.leaves:
                    leaf._pending_drop = False
                raise RuntimeError(
                    f"nvme optimizer-state swap-out failed ({failures} writes); "
                    "in-RAM moments retained")
            for leaf in self.leaves:
                if leaf._pending_drop:
                    leaf.states = [None] * self.n_states
                    if leaf.master_path:
                        leaf.master = None
                    leaf._pending_drop = False
        self.kernel.step_count = step_shared

    # --- views ---------------------------------------------------------------
    def _load_master(self, leaf: _LeafState) -> np.ndarray:
        if leaf.master is not None:
            return leaf.master
        buf = np.empty(leaf.shape, np.float32)
        if self.aio.wait(self.aio.async_pread(buf, leaf.master_path)):
            raise RuntimeError("nvme master swap-in failed")
        return buf

    def iter_masters(self):
        """Yield (idx, fp32 master) one leaf at a time — NVMe masters stream
        through a transient buffer instead of all materializing at once (the
        point of swap_masters for weights-bigger-than-RAM-budget runs)."""
        for leaf in self.leaves:
            yield leaf.idx, self._load_master(leaf)

    def masters(self) -> List[np.ndarray]:
        """All masters materialized (checkpoint-save path: transient RAM cost
        of the full fp32 set when masters live on NVMe)."""
        return [self._load_master(l) for l in self.leaves]

    def leaf_shapes(self) -> List[tuple]:
        return [l.shape for l in self.leaves]

    def shadows(self, dtype: str = "bfloat16") -> List[np.ndarray]:
        """Compute-dtype shadow copies for the host→device transfer."""
        cast = to_bf16 if dtype in ("bfloat16", "bf16") else \
            (lambda a: a.astype(dtype))
        return [cast(m) for _, m in self.iter_masters()]

    # --- persistence (consumed by checkpoint/engine.py) ----------------------
    def _materialized_states(self, leaf: _LeafState) -> List[np.ndarray]:
        if leaf.nvme and leaf.states[0] is None:
            reqs = self._swap_in([leaf])
            for r in reqs:
                if self.aio.wait(r):
                    raise RuntimeError("nvme swap-in failed during state export")
        return [np.asarray(s) for s in leaf.states]

    def _store_master(self, leaf: _LeafState, value: np.ndarray):
        value = np.ascontiguousarray(value, np.float32).reshape(leaf.shape)
        if leaf.master_path:
            if self.aio.wait(self.aio.async_pwrite(value, leaf.master_path)):
                raise RuntimeError("nvme master swap-out failed")
            leaf.master = None
        elif leaf.master is not None:
            np.copyto(leaf.master, value)
        else:
            leaf.master = value.copy()

    def state_dict(self) -> Dict[str, Any]:
        return {
            "step_count": int(self.kernel.step_count),
            "masters": self.masters(),
            "states": [self._materialized_states(l) for l in self.leaves],
        }

    def load_state_dict(self, sd: Dict[str, Any]):
        self.kernel.step_count = int(sd["step_count"])
        for leaf, master, states in zip(self.leaves, sd["masters"], sd["states"]):
            self._store_master(leaf, np.asarray(master, np.float32))
            buffers = [np.ascontiguousarray(s, np.float32).reshape(leaf.shape)
                       for s in states]
            if leaf.nvme:
                for s, buf in enumerate(buffers):
                    self.aio.async_pwrite(buf, leaf.paths[s])
                if self.aio.drain():
                    raise RuntimeError("nvme state restore failed")
                leaf.states = [None] * self.n_states
            else:
                leaf.states = buffers

    def set_masters(self, new_masters: List[np.ndarray], reset_moments: bool = False):
        """Overwrite masters (checkpoint-load resync). ``reset_moments`` zeroes
        the moments when the checkpoint carried none."""
        for leaf, m in zip(self.leaves, new_masters):
            self._store_master(leaf, np.asarray(m, np.float32))
            if reset_moments:
                if leaf.nvme:
                    zeros = np.zeros(leaf.shape, np.float32)
                    for path in leaf.paths:
                        self.aio.async_pwrite(zeros, path)
                    if self.aio.drain():
                        raise RuntimeError("nvme moment reset failed")
                    leaf.states = [None] * self.n_states
                else:
                    leaf.states = [np.zeros(leaf.shape, np.float32)
                                   for _ in range(self.n_states)]
