"""ZeRO-Offload / ZeRO-Infinity: host-DRAM and NVMe optimizer-state tiers.

Reference analogs:
- ZeRO-Offload: optimizer states + fp32 master params in host memory, CPU fused
  Adam update (``runtime/zero/offload_config.py``, ``ops/adam/cpu_adam.py``)
- ZeRO-Infinity: states on NVMe, swapped in/out per sub-group around the update
  (``runtime/swap_tensor/partitioned_optimizer_swapper.py:29`` and the
  double-buffered ``pipelined_optimizer_swapper.py``), over the aio engine

TPU-native shape: the device keeps compute-dtype (bf16) params and produces grads
under jit; the host keeps fp32 master params + Adam moments as numpy arrays and
runs the fused C++ CPU-Adam kernel; updated masters stream back as a bf16 shadow.
With NVMe enabled, moments live in per-leaf files; sub-groups are prefetched with
the async engine while the previous sub-group updates (Infinity's pipelined
swapper). Twin-Flow (``ratio`` < 1, reference ZeRO-Offload++ engine.py:757) keeps
the first ``1-ratio`` fraction of sub-groups permanently in host RAM.
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.config.config import OffloadConfig
from deepspeed_tpu.ops.async_io import AsyncIOHandle
from deepspeed_tpu.ops.cpu_adam import CPUAdam
from deepspeed_tpu.utils.logging import log_dist


class _LeafState:
    """Host state for one parameter leaf."""

    def __init__(self, idx: int, master: np.ndarray, nvme_dir: Optional[str]):
        self.idx = idx
        self.master = master                       # fp32, host-resident always
        self.nvme_dir = nvme_dir
        self.nvme = nvme_dir is not None
        if self.nvme:
            self.m_path = os.path.join(nvme_dir, f"exp_avg_{idx}.bin")
            self.v_path = os.path.join(nvme_dir, f"exp_avg_sq_{idx}.bin")
            self.m: Optional[np.ndarray] = None    # swapped in on demand
            self.v: Optional[np.ndarray] = None
        else:
            self.m = np.zeros_like(master)
            self.v = np.zeros_like(master)


class HostOffloadOptimizer:
    """Fused host Adam over offloaded states, with optional NVMe sub-group swap.

    Single-controller / per-process shard semantics: each process updates the
    params it addresses (multi-host runs shard leaves over processes upstream).
    """

    def __init__(self, params_host: List[np.ndarray], opt_params: Dict[str, Any],
                 offload: OffloadConfig, sub_group_size: int = 4):
        self.adam = CPUAdam(
            lr=opt_params.get("lr", 1e-3),
            betas=tuple(opt_params.get("betas", (0.9, 0.999))),
            eps=opt_params.get("eps", 1e-8),
            weight_decay=opt_params.get("weight_decay", 0.0),
            adamw_mode=opt_params.get("adam_w_mode", True))
        self.offload = offload
        nvme_dir = None
        if offload.device == "nvme":
            nvme_dir = os.path.join(offload.nvme_path or "/tmp/dstpu_nvme",
                                    f"proc{jax.process_index()}")
            os.makedirs(nvme_dir, exist_ok=True)
            self.aio = AsyncIOHandle(num_threads=offload.buffer_count * 2)
        self.leaves = [
            _LeafState(i, np.ascontiguousarray(p, dtype=np.float32),
                       # Twin-Flow partial offload: first (1-ratio) leaves pinned in RAM
                       nvme_dir if (nvme_dir and i >= (1.0 - offload.ratio) *
                                    len(params_host)) else None)
            for i, p in enumerate(params_host)]
        if nvme_dir:
            # initialize moment files; buffers must outlive the async writes
            keepalive = []
            for leaf in self.leaves:
                if leaf.nvme:
                    zeros = np.zeros_like(leaf.master)
                    keepalive.append(zeros)
                    self.aio.async_pwrite(zeros, leaf.m_path)
                    self.aio.async_pwrite(zeros, leaf.v_path)
            errors = self.aio.drain()
            if errors:
                raise RuntimeError(f"nvme moment-file init failed ({errors} errors)")
            del keepalive
        self.sub_group_size = max(1, sub_group_size)
        log_dist(f"host offload optimizer: device={offload.device} "
                 f"leaves={len(self.leaves)} ratio={offload.ratio}", ranks=[0])

    # --- NVMe swap (reference: _prepare_sub_group / _release_sub_group) -----
    def _swap_in(self, group: List[_LeafState]) -> List[int]:
        reqs = []
        for leaf in group:
            if leaf.nvme and leaf.m is None:
                leaf.m = np.empty_like(leaf.master)
                leaf.v = np.empty_like(leaf.master)
                reqs.append(self.aio.async_pread(leaf.m, leaf.m_path))
                reqs.append(self.aio.async_pread(leaf.v, leaf.v_path))
        return reqs

    def _swap_out(self, group: List[_LeafState]):
        for leaf in group:
            if leaf.nvme:
                self.aio.async_pwrite(leaf.m, leaf.m_path)
                self.aio.async_pwrite(leaf.v, leaf.v_path)
                # buffers dropped after writes drain (see step barrier)
                leaf._pending_drop = True

    def step(self, grads_host: List[np.ndarray], lr: Optional[float] = None):
        """One fused update over all leaves, sub-group pipelined when on NVMe
        (reference: pipelined_optimizer_swapper double buffering)."""
        groups = [self.leaves[i:i + self.sub_group_size]
                  for i in range(0, len(self.leaves), self.sub_group_size)]
        grad_groups = [grads_host[i:i + self.sub_group_size]
                       for i in range(0, len(grads_host), self.sub_group_size)]
        step_shared = self.adam.step_count + 1

        pending: List[int] = self._swap_in(groups[0]) if groups else []
        for gi, (group, ggrads) in enumerate(zip(groups, grad_groups)):
            for r in pending:
                if self.aio.wait(r):
                    raise RuntimeError("nvme optimizer-state swap-in failed")
            # prefetch next sub-group while this one updates
            pending = self._swap_in(groups[gi + 1]) if gi + 1 < len(groups) else []
            for leaf, g in zip(group, ggrads):
                self.adam.step_count = step_shared - 1
                self.adam.step(leaf.master.ravel(),
                               np.ascontiguousarray(g, np.float32).ravel(),
                               leaf.m.ravel(), leaf.v.ravel(), lr=lr)
            self._swap_out(group)
        if hasattr(self, "aio"):
            self.aio.drain()
            for leaf in self.leaves:
                if getattr(leaf, "_pending_drop", False):
                    leaf.m = None
                    leaf.v = None
                    leaf._pending_drop = False
        self.adam.step_count = step_shared

    def masters(self) -> List[np.ndarray]:
        return [l.master for l in self.leaves]
