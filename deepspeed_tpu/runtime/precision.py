"""Mixed precision: fp32 master weights, bf16/fp16 compute, dynamic loss scaling.

Reference analogs:
- ``runtime/fp16/loss_scaler.py:91`` ``DynamicLossScaler`` (scale up after
  ``scale_window`` good steps, scale down on overflow with hysteresis)
- ``runtime/fp16/fused_optimizer.py:33`` ``FP16_Optimizer`` (fp32 master weights)
- ``runtime/bf16_optimizer.py:34`` ``BF16_Optimizer`` (fp32 master + fp32 grad accum)

TPU-native shape: master params stay fp32 in the engine state; the forward pass casts
to the compute dtype at trace time, so XLA keeps matmuls in bf16 on the MXU while the
optimizer update runs fp32. The loss scaler is a *functional* state threaded through
the jitted train step (no Python-side branching — overflow handling is ``jnp.where``).
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import FP16Config


class LossScaleState(NamedTuple):
    """Dynamic loss-scale state (all jnp scalars: jit-carriable)."""
    scale: jnp.ndarray          # current loss scale (fp32)
    good_steps: jnp.ndarray     # consecutive overflow-free steps (int32)
    hysteresis: jnp.ndarray     # remaining overflow tolerance (int32)


def init_loss_scale(cfg: FP16Config) -> LossScaleState:
    if not cfg.enabled:
        return LossScaleState(jnp.float32(1.0), jnp.int32(0), jnp.int32(1))
    scale = cfg.loss_scale if cfg.loss_scale > 0 else float(2 ** cfg.initial_scale_power)
    return LossScaleState(jnp.float32(scale), jnp.int32(0), jnp.int32(cfg.hysteresis))


def update_loss_scale(state: LossScaleState, overflow: jnp.ndarray,
                      cfg: FP16Config) -> LossScaleState:
    """One dynamic-loss-scale transition (reference: loss_scaler.py:171 update_scale).

    Static scale (cfg.loss_scale > 0) passes through unchanged.
    """
    if not cfg.enabled or not cfg.dynamic:
        return state
    scale, good, hyst = state

    def on_overflow():
        new_hyst = hyst - 1
        drop = new_hyst <= 0
        new_scale = jnp.where(drop, jnp.maximum(scale / 2.0, cfg.min_loss_scale), scale)
        reset_hyst = jnp.where(drop, jnp.int32(cfg.hysteresis), new_hyst)
        return LossScaleState(new_scale, jnp.int32(0), reset_hyst)

    def on_good():
        grown = good + 1 >= cfg.loss_scale_window
        new_scale = jnp.where(grown, scale * 2.0, scale)
        new_good = jnp.where(grown, jnp.int32(0), good + 1)
        # reference loss_scaler.py: consecutive_hysteresis=True refills the
        # tolerance on every overflow-free step; False refills only when the
        # scale grows at the window boundary.
        if cfg.consecutive_hysteresis:
            new_hyst = jnp.int32(cfg.hysteresis)
        else:
            new_hyst = jnp.where(grown, jnp.int32(cfg.hysteresis), hyst)
        return LossScaleState(new_scale, new_good, new_hyst)

    return jax.tree.map(lambda a, b: jnp.where(overflow, a, b), on_overflow(), on_good())


def has_inf_or_nan(grads: Any) -> jnp.ndarray:
    """Global overflow check (reference: stage3.py:2221 _has_inf_or_nan /
    CheckOverflow runtime/utils.py:181). Under SPMD+jit the result is already
    globally consistent — no extra allreduce needed."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.bool_(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l.astype(jnp.float32)))) for l in leaves]
    # dslint: disable=DS003 -- device-side flag BY DESIGN: this runs inside
    # the jitted step, so the traced jnp.bool_ is the product (bool() here
    # would be a tracer error); the host boundary converts at readback
    return jnp.any(jnp.stack(flags))


def cast_to_compute(params: Any, dtype) -> Any:
    """Cast fp32 master params to the compute dtype for the forward pass. Integer /
    bool leaves (embedding tables are float; step counters etc.) pass through."""
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree.map(cast, params)


def global_grad_norm(grads: Any) -> jnp.ndarray:
    """L2 norm over all grad leaves (reference: runtime/utils.py clip_grad_norm_ —
    but MP-awareness is free here: under jit the grads are global values)."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(grads)]
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def clip_by_global_norm(grads: Any, max_norm: float) -> Tuple[Any, jnp.ndarray]:
    """Returns (clipped grads, pre-clip global norm)."""
    norm = global_grad_norm(grads)
    if max_norm <= 0:
        return grads, norm
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * factor).astype(g.dtype),
                        grads), norm
