"""Data loading.

Reference analog: ``runtime/dataloader.py:41,17`` (``DeepSpeedDataLoader`` with auto
distributed sampler, ``RepeatingLoader``). On TPU the engine consumes *global*
batches (every process feeds its shard; single-process feeds the whole batch and the
engine shards it onto the mesh), so the loader's job is batching + per-process
sharding + repeat.
"""

import queue
import threading
from typing import Any, Callable, Iterator, NamedTuple, Optional, Sequence

import numpy as np

from deepspeed_tpu.telemetry.tracer import get_tracer


class StagedBatch(NamedTuple):
    """A batch already placed on the mesh (device-resident, correctly
    sharded). ``train_batch`` consumes it directly, skipping its own
    ``_shard_batch`` — the marker that lets the prefetch thread do the
    host→device transfer one step ahead of compute."""
    arrays: Any


class PrefetchLoader:
    """Background-thread prefetch with a bounded ready-buffer (``depth=2`` is
    the classic double buffer).

    A single worker thread pulls items from ``source`` in order, optionally
    transforms them via ``stage_fn`` (the engine passes its
    ``_shard_batch``/``device_put`` staging so the H2D transfer of batch N+1
    overlaps compute of batch N), and parks up to ``depth`` ready items.
    Because there is exactly one worker consuming ``source`` sequentially,
    the yielded order is identical to iterating ``source`` directly —
    prefetch on/off is batch-for-batch deterministic. A ``stage_fn`` or
    ``source`` exception is re-raised at the consuming ``__next__``.
    """

    _DONE = object()

    def __init__(self, source, stage_fn: Optional[Callable] = None,
                 depth: int = 2):
        self._source = iter(source)
        self._stage_fn = stage_fn
        self._tracer = get_tracer()
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._closed = threading.Event()   # set by close(), read by worker
        self._done = False
        self._thread = threading.Thread(
            target=self._worker, name="dstpu-prefetch", daemon=True)
        self._thread.start()

    def _worker(self):
        def _put(item) -> bool:
            # bounded-wait put so close() can always terminate the worker
            while not self._closed.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        tr = self._tracer
        try:
            while not self._closed.is_set():
                try:
                    with tr.span("prefetch/next", cat="data"):
                        item = next(self._source)
                except StopIteration:
                    break
                if self._stage_fn is not None:
                    with tr.span("prefetch/stage", cat="data"):
                        item = self._stage_fn(item)
                if not _put(item):   # blocks while `depth` batches are ready
                    return
            _put(self._DONE)
        except BaseException as e:   # surfaced at the consumer's __next__
            _put(e)
            # terminate the stream: a consumer that swallows the error and
            # keeps pulling gets StopIteration, never a permanent hang
            _put(self._DONE)

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        if self._done:          # exhaustion is sticky: a drained stream
            raise StopIteration  # keeps raising instead of blocking forever
        item = self._q.get()
        if item is self._DONE:
            self._done = True
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self):
        """Stop the worker and drop buffered batches (used when the engine
        switches data iterators or is reconfigured)."""
        self._closed.set()
        self._done = True
        while True:                  # unblock a producer stuck on put()
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)


class RepeatingLoader:
    """reference: runtime/dataloader.py:17 — wrap an iterator to restart on
    StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedTPUDataLoader:
    """Minimal batching loader over an indexable dataset of pytrees.

    ``process_shard``: with multi-host training each process loads
    1/process_count of every global batch (the distributed-sampler analog).
    """

    def __init__(self, dataset: Sequence, batch_size: int,
                 collate_fn: Optional[Callable] = None, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = True,
                 process_index: int = 0, process_count: int = 1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or self._default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.process_index = process_index
        self.process_count = process_count
        self.epoch = 0
        if batch_size % process_count != 0:
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"process_count {process_count}")
        self.local_batch = batch_size // process_count

    @staticmethod
    def _default_collate(samples):
        import jax
        return jax.tree.map(lambda *xs: np.stack(xs), *samples)

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[Any]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        self.epoch += 1
        n_full = len(order) // self.batch_size
        for b in range(n_full):
            global_idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            local = global_idx[self.process_index::self.process_count]
            yield self.collate_fn([self.dataset[int(i)] for i in local])
        remainder = len(order) % self.batch_size
        if remainder and not self.drop_last:
            # final partial batch (note: a different batch shape triggers one extra
            # XLA compile; prefer drop_last=True for fixed-shape training)
            tail = order[n_full * self.batch_size:]
            tail = tail[:len(tail) - (len(tail) % self.process_count)] \
                if len(tail) >= self.process_count else tail
            local = tail[self.process_index::self.process_count]
            if len(local):
                yield self.collate_fn([self.dataset[int(i)] for i in local])
