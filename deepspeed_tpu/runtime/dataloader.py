"""Data loading.

Reference analog: ``runtime/dataloader.py:41,17`` (``DeepSpeedDataLoader`` with auto
distributed sampler, ``RepeatingLoader``). On TPU the engine consumes *global*
batches (every process feeds its shard; single-process feeds the whole batch and the
engine shards it onto the mesh), so the loader's job is batching + per-process
sharding + repeat.
"""

from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np


class RepeatingLoader:
    """reference: runtime/dataloader.py:17 — wrap an iterator to restart on
    StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedTPUDataLoader:
    """Minimal batching loader over an indexable dataset of pytrees.

    ``process_shard``: with multi-host training each process loads
    1/process_count of every global batch (the distributed-sampler analog).
    """

    def __init__(self, dataset: Sequence, batch_size: int,
                 collate_fn: Optional[Callable] = None, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = True,
                 process_index: int = 0, process_count: int = 1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or self._default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.process_index = process_index
        self.process_count = process_count
        self.epoch = 0
        if batch_size % process_count != 0:
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"process_count {process_count}")
        self.local_batch = batch_size // process_count

    @staticmethod
    def _default_collate(samples):
        import jax
        return jax.tree.map(lambda *xs: np.stack(xs), *samples)

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[Any]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        self.epoch += 1
        n_full = len(order) // self.batch_size
        for b in range(n_full):
            global_idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            local = global_idx[self.process_index::self.process_count]
            yield self.collate_fn([self.dataset[int(i)] for i in local])
        remainder = len(order) % self.batch_size
        if remainder and not self.drop_last:
            # final partial batch (note: a different batch shape triggers one extra
            # XLA compile; prefer drop_last=True for fixed-shape training)
            tail = order[n_full * self.batch_size:]
            tail = tail[:len(tail) - (len(tail) % self.process_count)] \
                if len(tail) >= self.process_count else tail
            local = tail[self.process_index::self.process_count]
            if len(local):
                yield self.collate_fn([self.dataset[int(i)] for i in local])
