"""Hybrid engine — RLHF train ↔ generate on shared weights.

Reference analog: ``deepspeed/runtime/hybrid_engine.py:30``
(``DeepSpeedHybridEngine``): flips a ZeRO-3 training model into
inference-kernel containers for ``generate`` (:168) and back for training,
fusing/unfusing LoRA, reusing the same weights, and tracking per-phase latency.

TPU-native shape: no module swapping — the training params (fp32 masters,
fsdp-sharded) and the inference params (bf16) are two *views* of one logical
weight set. ``generate()`` lazily builds a FastGen ``InferenceEngineV2`` (paged
KV cache + continuous batching) over a compute-dtype cast of the current
training params; after any training step the cast is refreshed (one jitted
cast, sharded → sharded, no host round-trip). LoRA adapters are fused into the
base weights for the generation view (reference ``fuse_lora_weight``) and the
training tree is left untouched.
"""

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine
from deepspeed_tpu.utils.logging import log_dist

LORA_A = "lora_a"
LORA_B = "lora_b"


def fuse_lora_params(params: Any, scaling: float = 1.0) -> Any:
    """Fuse LoRA adapters into their sibling base kernels (reference:
    hybrid_engine fuse_lora / _fuse_lora_weight): any dict node holding
    ``lora_a``/``lora_b`` next to a 2-D ``kernel``/``weight`` gets
    ``base + a @ b * scaling``; adapters are dropped from the fused view."""
    if not isinstance(params, dict):
        return params
    out = {}
    keys = set(params.keys())
    if LORA_A in keys and LORA_B in keys:
        base_key = next((k for k in ("kernel", "weight", "w") if k in keys), None)
        a, b = params[LORA_A], params[LORA_B]
        for k in keys - {LORA_A, LORA_B}:
            if k == base_key:
                out[k] = (params[k].astype(jnp.float32)
                          + (a.astype(jnp.float32) @ b.astype(jnp.float32))
                          * scaling).astype(params[k].dtype)
            else:
                out[k] = fuse_lora_params(params[k], scaling)
        if base_key is None:
            # no sibling base — keep adapters (caller consumes them directly)
            out[LORA_A], out[LORA_B] = a, b
        return out
    return {k: fuse_lora_params(v, scaling) for k, v in params.items()}


class DeepSpeedTPUHybridEngine(DeepSpeedTPUEngine):
    """Training engine + ``generate`` (reference DeepSpeedHybridEngine)."""

    def __init__(self, *args, hybrid_config: Optional[Dict[str, Any]] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        hc = hybrid_config or {}
        self.max_out_tokens = int(hc.get("max_out_tokens", 512))
        self.release_inference_cache = bool(hc.get("release_inference_cache", False))
        # scaling priority: explicit hybrid_config > the model's own LoRAConfig
        # > the global LoRAConfig default (alpha/r) — a model whose adapters use
        # a non-default alpha/r would otherwise get a wrong fused view
        from deepspeed_tpu.linear.config import LoRAConfig as _LC
        model_lc = getattr(self.model, "lora_config", None) or \
            getattr(getattr(self.model, "config", None), "lora_config", None)
        if "lora_scaling" in hc:
            self.lora_scaling = float(hc["lora_scaling"])
        elif isinstance(model_lc, _LC):
            self.lora_scaling = float(model_lc.lora_alpha / model_lc.lora_r)
        else:
            _lc = _LC()
            self.lora_scaling = float(_lc.lora_alpha / _lc.lora_r)
            # only meaningful (and worth a warning) if the model actually has
            # LoRA adapters that will be fused with this default scaling
            try:
                has_lora = any(
                    LORA_A in p for p in (
                        "/".join(str(getattr(kk, "key", kk)) for kk in path)
                        for path, _ in jax.tree_util.tree_flatten_with_path(
                            self.state.params)[0]))
            except Exception:
                has_lora = False
            if has_lora:
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    "hybrid engine: model has LoRA adapters but no "
                    "lora_scaling in hybrid_config and no LoRAConfig on the "
                    f"model; fusing with the global default alpha/r = "
                    f"{self.lora_scaling}")
        self._infer_engine = None
        self._infer_params = None
        self._weights_version = -1

        scaling, dtype = self.lora_scaling, self.compute_dtype

        def _to_infer(p):
            fused = fuse_lora_params(p, scaling)
            return jax.tree.map(
                lambda x: x.astype(dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, fused)
        # built once: refreshes hit the jit cache instead of retracing per step
        self._to_infer_fn = jax.jit(_to_infer)
        # per-phase latency bookkeeping (reference hybrid_engine.py:54-60)
        self._generate_latency = 0.0
        self._training_latency = 0.0
        # flip = train→generate view refresh (cast + LoRA fuse + engine swap);
        # the reference instruments this per phase (_t_start/_t_gen family) —
        # it is the RLHF phase-switch cost a user tunes release_inference_cache
        # against
        self._flip_latency = 0.0
        self._flip_count = 0
        self._iters = 0

    # ------------------------------------------------------------------
    def _model_config(self):
        cfg = getattr(self.model, "config", None) or getattr(self.model, "cfg", None)
        if cfg is None:
            raise ValueError(
                "hybrid engine generate() needs a model with a .config "
                "(LlamaForCausalLM-style) to build the decode path")
        return cfg

    def _refresh_inference_view(self):
        """Re-cast the live training weights into the inference view (bf16 +
        fused LoRA). One jitted cast per refresh; shardings preserved."""
        if self._weights_version == self.global_steps and self._infer_engine:
            return
        t0 = time.time()
        self._infer_params = self._to_infer_fn(self.state.params)
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, V2EngineConfig)
        cfg = self._model_config()
        v2cfg = V2EngineConfig()
        if self._infer_engine is not None and not self.release_inference_cache:
            # keep the engine (and its compiled programs); swap weights only
            self._infer_engine.params = self._infer_params
        else:
            self._infer_engine = InferenceEngineV2(self._infer_params, cfg, v2cfg)
        self._weights_version = self.global_steps
        dt = time.time() - t0
        self._flip_latency += dt
        self._flip_count += 1
        log_dist(f"hybrid: refreshed inference view at step {self.global_steps} "
                 f"({dt:.2f}s)", ranks=[0])

    # ------------------------------------------------------------------
    def generate(self, prompt_tokens: Sequence[int], max_new_tokens: int = 32,
                 uid: int = 0) -> List[int]:
        """Generate with the current weights (reference: hybrid_engine.py:168).
        Accepts one prompt (list of ids) or a batch (list of lists)."""
        t0 = time.time()
        self._refresh_inference_view()
        eng = self._infer_engine
        if prompt_tokens and isinstance(prompt_tokens[0], (list, tuple)):
            # batched rollout through continuous batching: admit every prompt,
            # then step the engine — decodes run as one padded batch per token
            # instead of per-prompt loops
            budget = min(max_new_tokens, self.max_out_tokens)
            uids = [uid + i for i in range(len(prompt_tokens))]
            eng.put(uids, [list(p) for p in prompt_tokens])
            seqs = [eng.state.get(u) for u in uids]
            while any(len(s.generated) < budget and not s.done for s in seqs):
                eng.step()
            result = [eng.flush(u)[:budget] for u in uids]
        else:
            result = eng.generate(
                list(prompt_tokens),
                max_new_tokens=min(max_new_tokens, self.max_out_tokens), uid=uid)
        self._generate_latency += time.time() - t0
        self._iters += 1
        return result

    def train_batch(self, *args, **kwargs):
        t0 = time.time()
        out = super().train_batch(*args, **kwargs)
        self._training_latency += time.time() - t0
        if self.release_inference_cache:
            # free the paged-KV pool AND the bf16 weight copy for the train phase
            self._infer_engine = None
            self._infer_params = None
        return out

    # reference latency accessors (hybrid_engine _t_start/_total_latency family)
    @property
    def generate_latency(self) -> float:
        return self._generate_latency

    @property
    def training_latency(self) -> float:
        return self._training_latency

    @property
    def flip_latency(self) -> float:
        """Cumulative train→generate view-refresh seconds."""
        return self._flip_latency

    @property
    def flip_count(self) -> int:
        return self._flip_count

    def latency_report(self) -> Dict[str, float]:
        """Per-phase totals + mean flip cost (reference per-phase printout)."""
        return {
            "train_s": self._training_latency,
            "generate_s": self._generate_latency,
            "flip_s": self._flip_latency,
            "flips": float(self._flip_count),
            "flip_mean_s": (self._flip_latency / self._flip_count
                            if self._flip_count else 0.0),
        }
