"""Tiled linear layers — split huge matmuls to bound peak memory.

Reference analog: ``deepspeed/runtime/zero/tiling.py:32`` (``TiledLinear`` —
splits a Linear into in/out-feature tiles so ZeRO-3 fetches and frees one tile
at a time instead of materializing the full weight).

TPU shape: parameters are stored as tile stacks ``[out_tiles, in_tiles,
in/in_tiles, out/out_tiles]`` and contracted with a ``lax.scan`` over input
tiles (optionally rematerialized), so the live working set is one tile's
activation product; ZeRO-3 sharding rules apply per-leaf as usual, and XLA
schedules per-tile all-gathers just-in-time the way the reference's fetch/
release hooks do.
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class TiledLinear(nn.Module):
    """y = x @ W (+ b) with W split into (in_splits x out_splits) tiles."""
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        d_in = x.shape[-1]
        if d_in % self.in_splits or self.features % self.out_splits:
            raise ValueError(
                f"features {d_in}->{self.features} not divisible by splits "
                f"({self.in_splits}, {self.out_splits})")
        ti, to = d_in // self.in_splits, self.features // self.out_splits
        kernel = self.param(
            "kernel", self.kernel_init,
            (self.in_splits, self.out_splits, ti, to), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32) if self.use_bias \
            else None

        xt = x.reshape(*x.shape[:-1], self.in_splits, ti)

        def in_tile(acc, tile):
            k_i, x_i = tile          # [out_splits, ti, to], [..., ti]
            y = jnp.einsum("...i,oij->...oj", x_i.astype(self.dtype),
                           k_i.astype(self.dtype))
            return acc + y, None

        acc0 = jnp.zeros((*x.shape[:-1], self.out_splits, to), self.dtype)
        xt_scan = jnp.moveaxis(xt, -2, 0)          # [in_splits, ..., ti]
        acc, _ = jax.lax.scan(in_tile, acc0, (kernel, xt_scan))
        y = acc.reshape(*x.shape[:-1], self.features)
        if bias is not None:
            y = y + bias.astype(self.dtype)
        return y


def split_tiled_weight(full_kernel, in_splits: int, out_splits: int):
    """[D_in, D_out] dense kernel -> TiledLinear's [in_splits, out_splits,
    ti, to] stack (reference: TiledLinear.copy_params_from)."""
    d_in, d_out = full_kernel.shape
    ti, to = d_in // in_splits, d_out // out_splits
    k = full_kernel.reshape(in_splits, ti, out_splits, to)
    return jnp.transpose(k, (0, 2, 1, 3))
