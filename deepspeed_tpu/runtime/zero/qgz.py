"""qgZ — quantized gradient reduction with int8 on the wire.

Reference analog: ZeRO++ quantized-gradient collectives —
``deepspeed/runtime/comm/coalesced_collectives.py:31``
(``all_to_all_quant_reduce``: int8 all-to-all within the node,
dequant-reduce, second quantized hop across nodes) backed by
``csrc/quantization/quant_reduce.cu``.

TPU mapping. The reference applies qgZ to the *replica* gradient
all-reduce — the DP hop that crosses the slow wire (inter-node) while hpZ
keeps parameter shards within the fast wire (intra-node). The SPMD engine
has the same split: the batch axes over which every parameter is
**replicated** (``data``, and ``fsdp_out`` under MiCS/hpZ-style grouping)
carry a pure gradient all-reduce, while the axes that shard parameters
(``fsdp``) get their reduction fused into XLA's backward as an ICI
reduce-scatter. So the int8-wire path here covers exactly the replica
axes: the gradient phase runs inside a *partial-manual* ``jax.shard_map``
(replica axes manual, everything else — fsdp gathers, tensor-parallel
collectives — stays XLA-auto), computes per-device partial gradients, and
reduces them with a hierarchical int8 reduce-scatter + int8 regather. The
wire carries int8 codes + fp32 per-row scales in both directions: ~4x
fewer bytes than an fp32 all-reduce, the same saving the reference claims
for qgZ.

When the mesh has no replica batch axis (pure-fsdp ZeRO-3 on one slice),
there is no replica all-reduce to compress — the engine falls back to the
int8 round-trip *numerics* simulation so the flag's convergence contract
still holds (see ``engine._grads_one_micro``).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# leaves below this many elements psum in full precision: norm scales and
# biases are bandwidth-irrelevant and the most quantization-sensitive
# (the reference buckets everything; skipping tiny leaves is strictly
# less noise for ~zero wire cost)
MIN_QUANT_SIZE = 2048


def _spec_axes(spec) -> Tuple[str, ...]:
    axes = []
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            axes.append(a)
    return tuple(axes)


def replica_grad_axes(mesh: Mesh, batch_spec, param_shardings) -> Tuple[str, ...]:
    """The batch axes whose gradient reduction is a pure replica all-reduce:
    present in the batch sharding, absent from every parameter sharding, and
    larger than 1. These are the axes the int8-wire reduction covers; axes
    that shard parameters (fsdp under ZeRO>=3) keep XLA's fused backward
    reduce-scatter on the fast wire."""
    used = set()
    for s in jax.tree.leaves(
            param_shardings, is_leaf=lambda x: isinstance(x, NamedSharding)):
        used.update(_spec_axes(s.spec))
    return tuple(a for a in _spec_axes(batch_spec)
                 if a not in used and mesh.shape.get(a, 1) > 1)


def manual_part(spec, manual_axes) -> P:
    """Project a PartitionSpec onto ``manual_axes`` — the in_spec a
    partial-manual shard_map needs for an input whose full sharding is
    ``spec`` (the remaining axes stay automatic)."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        kept = tuple(a for a in axes if a in manual_axes)
        out.append(kept if kept else None)
    return P(*out)


def quantized_grad_sync(grads, axes: Tuple[str, ...]):
    """Mean-reduce a gradient pytree over the manual ``axes`` with int8 on
    the wire. Must run inside a shard_map whose manual axes include ``axes``.

    A thin adapter over the comm compression layer: each large leaf rides
    ONE ``comm.quantized_all_reduce`` (int8 exchange + regather with
    per-chunk fp32 scales — ``comm/compress.py``, the single
    quantize/dequantize implementation), routed through the facade so
    commguard ``_record``, the heartbeat, and dstrace see the op with exact
    logical + wire byte counts. 1-D and tiny leaves take a full-precision
    pmean (norm scales and biases are bandwidth-irrelevant and the most
    quantization-sensitive — this adapter carries no error feedback), and
    so does any leaf whose padded wire payload would not actually beat the
    dense reduction (the old rows<world pad-blowup guard, generalized to
    the chunked codec)."""
    from deepspeed_tpu.comm.comm import quantized_all_reduce
    from deepspeed_tpu.comm.compress import (DEFAULT_CHUNK, axis_world,
                                             padded_elems, wire_payload_bytes)

    def sync(g):
        if g.ndim < 2 or g.size < MIN_QUANT_SIZE:
            return jax.lax.pmean(g, axes)
        wire = wire_payload_bytes(
            padded_elems(g.size, axis_world(axes), DEFAULT_CHUNK))
        if wire >= g.size * jnp.dtype(g.dtype).itemsize:
            return jax.lax.pmean(g, axes)
        out, _ = quantized_all_reduce(g.reshape(-1), axes)
        return out[:g.size].reshape(g.shape).astype(g.dtype)

    return jax.tree.map(sync, grads)


def wrap_grads_phase(grads_phase, mesh: Mesh, axes: Tuple[str, ...],
                     batch_spec, stacked: bool, sync_fn=None, ef_specs=None):
    """Wrap ``grads_phase(params, batch, rngs, scale) -> (loss, grads)`` in a
    partial-manual shard_map over the replica ``axes``: inside, gradients are
    per-device partials (no XLA psum over the manual axes), the loss is
    pmean'd and the gradients reduced by ``sync_fn(grads, batch)`` (default:
    ``quantized_grad_sync`` — the engine passes a composite that can also
    route embedding leaves through the sparse wire format). Everything else
    (fsdp parameter gathers, tensor collectives) stays XLA-auto.

    ``batch_spec`` is the per-microbatch sharding; ``stacked`` prepends the
    gas dimension. Returns a drop-in replacement for ``grads_phase`` whose
    outputs are replicated over ``axes`` (identical to the SPMD result,
    modulo the wire compression in use).

    ``ef_specs`` threads persistent error-feedback state (comm_compression)
    through the manual region: a pytree of PartitionSpecs matching the EF
    tree (each leaf manual over ``axes`` on its participant dim). When
    given, the wrapped fn is ``(params, batch, rngs, scale, ef) ->
    (loss, grads, new_ef)`` and ``sync_fn(grads, batch, ef)`` must return
    ``(grads, new_ef)``.
    """
    if not axes:
        return grads_phase
    if sync_fn is None:
        sync_fn = lambda grads, batch: quantized_grad_sync(grads, axes)  # noqa: E731

    def local_phase(params, batch, rngs, scale, *ef):
        # decorrelate dropout/noise across replicas: in auto-SPMD the random
        # bits are drawn per global batch position, but in here every replica
        # traces with the same key — fold the replica index in so masks
        # differ per shard like they do on the SPMD path
        idx = jnp.int32(0)
        for ax in axes:
            idx = idx * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
        if getattr(rngs, "ndim", 0) == 2:        # stacked [gas, 2] raw keys
            rngs = jax.vmap(jax.random.fold_in, in_axes=(0, None))(rngs, idx)
        else:                                     # single raw key
            rngs = jax.random.fold_in(rngs, idx)
        loss, grads = grads_phase(params, batch, rngs, scale)
        loss = jax.lax.pmean(loss, axes)
        if ef_specs is None:
            grads = sync_fn(grads, batch)
            return loss, grads
        grads, new_ef = sync_fn(grads, batch, ef[0])
        return loss, grads, new_ef

    bspec = manual_part(batch_spec, axes)
    if stacked:
        bspec = P(None, *bspec)
    in_specs = (P(), bspec, P(), P())
    out_specs = (P(), P())
    if ef_specs is not None:
        in_specs = in_specs + (ef_specs,)
        out_specs = out_specs + (ef_specs,)
    return jax.shard_map(
        local_phase, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names=frozenset(axes),
        check_vma=False)
