"""ZeRO stages as SPMD sharding policies.

Reference analog: ``deepspeed/runtime/zero/`` — ``stage_1_and_2.py:97``
(``DeepSpeedZeroOptimizer``: flatten + round-robin partition optimizer states, stage-2
grad-hook reduce-scatter) and ``stage3.py``/``partition_parameters.py`` (param
partitioning with allgather/release module hooks and a trace-based prefetcher).

On TPU none of that machinery is runtime code: a ZeRO stage is a **sharding policy** —
a rule assigning a ``PartitionSpec`` to every parameter / optimizer-state leaf over the
ZeRO mesh axes. XLA then emits exactly the collectives the reference implements by
hand (allgather params before use ≙ stage-3 fetch; psum_scatter of grads into the
shard ≙ stage-2 `average_tensor`; sharded optimizer update + allgather ≙ stage-1/2
step), scheduled and overlapped by the compiler instead of a Python prefetch queue.

  stage 0 — params, grads, optimizer states replicated over the DP axes
  stage 1 — optimizer states sharded over (fsdp_out, fsdp)
  stage 2 — + gradients reduce-scattered (same specs; XLA derives reduce-scatter
            from "grads consumed with sharded layout")
  stage 3 — + parameters sharded over (fsdp_out, fsdp) (FSDP)

Hierarchical variants use the split ZeRO world (mesh axes ``fsdp_out`` × ``fsdp``):

- **MiCS** (reference ``runtime/zero/mics.py:64``): everything sharded over the
  *inner* ``fsdp`` sub-axis only and replicated across ``fsdp_out`` — gathers ride
  ICI within the shard group; grad sync across groups is XLA's hierarchical psum
  (the reference's ``_hierarchical_all_gather_params`` by construction).
- **ZeRO++ hpZ** (reference ``partition_parameters.py:1664 _partition_param_sec``):
  masters/moments keep the full (fsdp_out, fsdp) shard for memory; the engine
  constrains the bf16 *compute* copy to the secondary spec (inner-only) so
  per-layer gathers stay within the node/slice.

Tensor-parallel sharding composes: a leaf annotated with a logical axis that maps to
``tensor`` keeps that axis, and fsdp shards a *different* dimension.
"""

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.comm.mesh import FSDP_AXES
from deepspeed_tpu.utils.logging import warning_once

# Minimum leaf size worth sharding; tiny leaves (biases, norms) stay replicated —
# the analog of the reference's persistent-param threshold
# (stage3 persistence_threshold keeps small params resident).
DEFAULT_MIN_SHARD_SIZE = 2 ** 11


def _choose_fsdp_dim(shape, fsdp_size: int, taken_dims) -> Optional[int]:
    """Pick the largest dimension divisible by the fsdp world size, preferring the
    first (row) dimension to keep matmul layouts MXU-friendly."""
    candidates = [d for d in range(len(shape))
                  if d not in taken_dims and shape[d] % fsdp_size == 0 and shape[d] >= fsdp_size]
    if not candidates:
        return None
    return max(candidates, key=lambda d: (shape[d], -d))


def _normalize_axes(fsdp_axes: Sequence[str]) -> Tuple:
    """A single axis goes in bare; several as a tuple entry."""
    axes = tuple(fsdp_axes)
    return axes[0] if len(axes) == 1 else axes


def param_partition_spec(shape, stage: int, fsdp_size: int,
                         tensor_spec: Optional[PartitionSpec] = None,
                         min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                         axis_sizes: Optional[dict] = None,
                         fsdp_axes: Sequence[str] = FSDP_AXES) -> PartitionSpec:
    """PartitionSpec for a parameter leaf under a given ZeRO stage.

    ``fsdp_size`` is the product extent of ``fsdp_axes`` (the ZeRO world this
    policy shards over — the full world by default, the inner sub-axis for MiCS).
    ``tensor_spec`` is an existing (tensor/expert/sequence) sharding from model
    annotations; fsdp sharding is layered on an unused dimension. Annotated axes
    that do not divide the dimension are dropped (e.g. GQA kv heads < tp degree —
    the reference AutoTP replicates in that case too).
    """
    ndim = len(shape)
    base = list(tensor_spec) if tensor_spec is not None else []
    base = base + [None] * (ndim - len(base))
    if axis_sizes:
        for i, ax in enumerate(base):
            if ax is not None and shape[i] % axis_sizes.get(ax, 1) != 0:
                warning_once(f"dim {i} of shape {shape} not divisible by "
                             f"{ax}={axis_sizes.get(ax)}; replicating that dim")
                base[i] = None
    if stage < 3 or fsdp_size <= 1:
        return PartitionSpec(*base) if any(a is not None for a in base) else PartitionSpec()
    if int(np.prod(shape)) < min_shard_size:
        return PartitionSpec(*base) if any(a is not None for a in base) else PartitionSpec()
    taken = {i for i, a in enumerate(base) if a is not None}
    dim = _choose_fsdp_dim(shape, fsdp_size, taken)
    if dim is None:
        warning_once(f"param of shape {shape} not divisible by fsdp={fsdp_size}; replicated")
        return PartitionSpec(*base) if any(a is not None for a in base) else PartitionSpec()
    base[dim] = _normalize_axes(fsdp_axes)
    return PartitionSpec(*base)


def optimizer_state_spec_fn(param_specs, stage: int, fsdp_size: int,
                            min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                            fsdp_axes: Sequence[str] = FSDP_AXES):
    """Build a function mapping an optimizer-state leaf (with a matching param leaf
    position) to its PartitionSpec. Optimizer moments share the param's shape, so:

      stage >= 1: moments sharded over the ZeRO world like a stage-3 param
      stage 3:    moments follow the (already fsdp-sharded) param spec exactly
      stage 0:    replicated / follow param's tensor spec
    """
    def spec_for(param_spec: PartitionSpec, shape) -> PartitionSpec:
        if stage == 0 or fsdp_size <= 1:
            return param_spec
        if stage >= 3:
            return param_spec  # param already carries fsdp
        # stage 1/2: shard moments even though params are replicated
        return param_partition_spec(shape, stage=3, fsdp_size=fsdp_size,
                                    tensor_spec=param_spec,
                                    min_shard_size=min_shard_size,
                                    fsdp_axes=fsdp_axes)
    return spec_for


def zero_fsdp_axes(mesh: Mesh, mics: bool = False) -> Tuple[Sequence[str], int]:
    """(axes, world) the ZeRO policy shards over: the full hierarchical world, or
    the inner sub-axis only under MiCS."""
    if mics:
        return ("fsdp",), mesh.shape["fsdp"]
    axes = tuple(a for a in FSDP_AXES if a in mesh.shape)
    world = int(np.prod([mesh.shape[a] for a in axes]))
    return axes, world


def zero_placement(mesh_shape: dict, stage: int,
                   offload_optimizer: str = "none",
                   offload_param: str = "none") -> dict:
    """The ZeRO placement signature derived from mesh + stage (automatic
    weight-update sharding: placement is a pure function of the mesh and
    the memory plan, never a hand-set table). Recorded in checkpoint
    provenance (``ds_meta.json``) and compared on mesh-portable resume so a
    changed tier/world is an explicit, logged transition — and an
    *incompatible* one a classified error instead of a shape crash."""
    sizes = {a: int(mesh_shape.get(a, 1) or 1) for a in FSDP_AXES}
    return {
        "stage": int(stage),
        "zero_world": int(np.prod(list(sizes.values()))),
        "sharded_axes": [a for a in FSDP_AXES if sizes[a] > 1],
        "offload_optimizer": str(offload_optimizer),
        "offload_param": str(offload_param),
    }


def build_param_shardings(params: Any, mesh: Mesh, stage: int,
                          tensor_rules: Optional[Callable] = None,
                          min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                          mics: bool = False):
    """Pytree of NamedShardings for the model params.

    ``tensor_rules(path, leaf) -> PartitionSpec | None`` supplies model-parallel
    shardings (the AutoTP analog — see deepspeed_tpu.module_inject.auto_tp).
    ``mics=True`` shards over the inner fsdp sub-axis only (replicated across
    ``fsdp_out`` shard groups).
    """
    fsdp_axes, fsdp_size = zero_fsdp_axes(mesh, mics=mics)
    axis_sizes = dict(mesh.shape)

    from deepspeed_tpu.utils.tree import tree_path_str
    from deepspeed_tpu.utils.z3_leaf_module import is_z3_leaf_path

    def leaf_spec(path, leaf):
        tspec = tensor_rules(path, leaf) if tensor_rules else None
        path_s = tree_path_str(path)
        # z3 leaf modules: subtree opted out of fsdp sharding (TP still applies)
        leaf_stage = 0 if is_z3_leaf_path(path_s) else stage
        return param_partition_spec(np.shape(leaf), leaf_stage, fsdp_size,
                                    tensor_spec=tspec,
                                    min_shard_size=min_shard_size,
                                    axis_sizes=axis_sizes, fsdp_axes=fsdp_axes)

    specs = jax.tree_util.tree_map_with_path(leaf_spec, params)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))


def secondary_partition_spec(spec: PartitionSpec) -> PartitionSpec:
    """ZeRO++ hpZ secondary spec: rewrite any dim sharded over the full
    hierarchical world to shard over the inner ``fsdp`` sub-axis only — the
    compute copy is then replicated across ``fsdp_out`` so per-layer gathers stay
    within the shard group (reference ``_partition_param_sec``,
    ``zero_hpz_partition_size``)."""
    def fix(entry):
        if isinstance(entry, (tuple, list)) and "fsdp" in entry:
            rest = tuple(a for a in entry if a not in FSDP_AXES)
            return rest + ("fsdp",) if rest else "fsdp"
        if entry in FSDP_AXES:
            return "fsdp"
        return entry
    return PartitionSpec(*[fix(e) for e in spec])


def build_secondary_shardings(param_shardings: Any, mesh: Mesh):
    """hpZ compute-copy shardings derived from the primary param shardings."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, secondary_partition_spec(s.spec)),
        param_shardings,
        is_leaf=lambda x: isinstance(x, NamedSharding))


def build_opt_state_shardings(opt_state: Any, params: Any, param_specs: Any,
                              mesh: Mesh, stage: int,
                              min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                              mics: bool = False):
    """Shardings for an optax state pytree: any leaf whose shape matches a param
    leaf's shape gets the corresponding (stage-aware) spec; scalars replicated.

    Optax states are pytrees whose array leaves are either param-shaped (moments,
    master copies) or scalars (step counts); we match by structure where possible and
    by shape as fallback.
    """
    fsdp_axes, fsdp_size = zero_fsdp_axes(mesh, mics=mics)
    spec_of = optimizer_state_spec_fn(param_specs, stage, fsdp_size, min_shard_size,
                                      fsdp_axes=fsdp_axes)

    flat_params, _ = jax.tree_util.tree_flatten(params)
    flat_specs, _ = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    shape_to_spec = {}
    for p, s in zip(flat_params, flat_specs):
        shape_to_spec.setdefault(np.shape(p), s)

    def state_leaf_spec(leaf):
        shape = np.shape(leaf)
        if len(shape) == 0:
            return PartitionSpec()
        if shape in shape_to_spec:
            return spec_of(shape_to_spec[shape], shape)
        # unmatched non-scalar leaf: auto-shard if big (e.g. flattened buffers)
        return param_partition_spec(shape, stage=3 if stage >= 1 else 0,
                                    fsdp_size=fsdp_size, min_shard_size=min_shard_size,
                                    fsdp_axes=fsdp_axes)

    specs = jax.tree.map(state_leaf_spec, opt_state)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))
