"""ZeRO stages as SPMD sharding policies.

Reference analog: ``deepspeed/runtime/zero/`` — ``stage_1_and_2.py:97``
(``DeepSpeedZeroOptimizer``: flatten + round-robin partition optimizer states, stage-2
grad-hook reduce-scatter) and ``stage3.py``/``partition_parameters.py`` (param
partitioning with allgather/release module hooks and a trace-based prefetcher).

On TPU none of that machinery is runtime code: a ZeRO stage is a **sharding policy** —
a rule assigning a ``PartitionSpec`` to every parameter / optimizer-state leaf over the
``fsdp`` mesh axis. XLA then emits exactly the collectives the reference implements by
hand (allgather params before use ≙ stage-3 fetch; psum_scatter of grads into the
shard ≙ stage-2 `average_tensor`; sharded optimizer update + allgather ≙ stage-1/2
step), scheduled and overlapped by the compiler instead of a Python prefetch queue.

  stage 0 — params, grads, optimizer states replicated over (data, fsdp)
  stage 1 — optimizer states sharded over fsdp
  stage 2 — + gradients reduce-scattered (same specs; XLA derives reduce-scatter
            from "grads consumed with sharded layout")
  stage 3 — + parameters sharded over fsdp (FSDP)

ZeRO++ hpZ (secondary shard within a node, ``partition_parameters.py:1664``) maps to
sharding over a *sub-axis* of fsdp (see ``hierarchical_axes``); MiCS
(``runtime/zero/mics.py:64``) is the same idea with replication across DCN slices.

Tensor-parallel sharding composes: a leaf annotated with a logical axis that maps to
``tensor`` keeps that axis, and fsdp shards a *different* dimension.
"""

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.utils.logging import warning_once

# Minimum leaf size worth sharding; tiny leaves (biases, norms) stay replicated —
# the analog of the reference's persistent-param threshold
# (stage3 persistence_threshold keeps small params resident).
DEFAULT_MIN_SHARD_SIZE = 2 ** 11


def _choose_fsdp_dim(shape, fsdp_size: int, taken_dims) -> Optional[int]:
    """Pick the largest dimension divisible by the fsdp axis size, preferring the
    first (row) dimension to keep matmul layouts MXU-friendly."""
    candidates = [d for d in range(len(shape))
                  if d not in taken_dims and shape[d] % fsdp_size == 0 and shape[d] >= fsdp_size]
    if not candidates:
        return None
    return max(candidates, key=lambda d: (shape[d], -d))


def param_partition_spec(shape, stage: int, fsdp_size: int,
                         tensor_spec: Optional[PartitionSpec] = None,
                         min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                         axis_sizes: Optional[dict] = None) -> PartitionSpec:
    """PartitionSpec for a parameter leaf under a given ZeRO stage.

    ``tensor_spec`` is an existing (tensor/expert/sequence) sharding from model
    annotations; fsdp sharding is layered on an unused dimension. Annotated axes
    that do not divide the dimension are dropped (e.g. GQA kv heads < tp degree —
    the reference AutoTP replicates in that case too).
    """
    ndim = len(shape)
    base = list(tensor_spec) if tensor_spec is not None else []
    base = base + [None] * (ndim - len(base))
    if axis_sizes:
        for i, ax in enumerate(base):
            if ax is not None and shape[i] % axis_sizes.get(ax, 1) != 0:
                warning_once(f"dim {i} of shape {shape} not divisible by "
                             f"{ax}={axis_sizes.get(ax)}; replicating that dim")
                base[i] = None
    if stage < 3 or fsdp_size <= 1:
        return PartitionSpec(*base) if any(a is not None for a in base) else PartitionSpec()
    if int(np.prod(shape)) < min_shard_size:
        return PartitionSpec(*base) if any(a is not None for a in base) else PartitionSpec()
    taken = {i for i, a in enumerate(base) if a is not None}
    dim = _choose_fsdp_dim(shape, fsdp_size, taken)
    if dim is None:
        warning_once(f"param of shape {shape} not divisible by fsdp={fsdp_size}; replicated")
        return PartitionSpec(*base) if any(a is not None for a in base) else PartitionSpec()
    base[dim] = "fsdp"
    return PartitionSpec(*base)


def optimizer_state_spec_fn(param_specs, stage: int, fsdp_size: int,
                            min_shard_size: int = DEFAULT_MIN_SHARD_SIZE):
    """Build a function mapping an optimizer-state leaf (with a matching param leaf
    position) to its PartitionSpec. Optimizer moments share the param's shape, so:

      stage >= 1: moments sharded over fsdp like a stage-3 param would be
      stage 3:    moments follow the (already fsdp-sharded) param spec exactly
      stage 0:    replicated / follow param's tensor spec
    """
    def spec_for(param_spec: PartitionSpec, shape) -> PartitionSpec:
        if stage == 0 or fsdp_size <= 1:
            return param_spec
        if stage >= 3:
            return param_spec  # param already carries fsdp
        # stage 1/2: shard moments even though params are replicated
        return param_partition_spec(shape, stage=3, fsdp_size=fsdp_size,
                                    tensor_spec=param_spec,
                                    min_shard_size=min_shard_size)
    return spec_for


def build_param_shardings(params: Any, mesh: Mesh, stage: int,
                          tensor_rules: Optional[Callable] = None,
                          min_shard_size: int = DEFAULT_MIN_SHARD_SIZE):
    """Pytree of NamedShardings for the model params.

    ``tensor_rules(path, leaf) -> PartitionSpec | None`` supplies model-parallel
    shardings (the AutoTP analog — see deepspeed_tpu.parallel.auto_tp).
    """
    fsdp_size = mesh.shape["fsdp"]
    axis_sizes = dict(mesh.shape)

    def leaf_spec(path, leaf):
        tspec = tensor_rules(path, leaf) if tensor_rules else None
        return param_partition_spec(np.shape(leaf), stage, fsdp_size, tensor_spec=tspec,
                                    min_shard_size=min_shard_size,
                                    axis_sizes=axis_sizes)

    specs = jax.tree_util.tree_map_with_path(leaf_spec, params)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))


def build_opt_state_shardings(opt_state: Any, params: Any, param_specs: Any,
                              mesh: Mesh, stage: int,
                              min_shard_size: int = DEFAULT_MIN_SHARD_SIZE):
    """Shardings for an optax state pytree: any leaf whose shape matches a param
    leaf's shape gets the corresponding (stage-aware) spec; scalars replicated.

    Optax states are pytrees whose array leaves are either param-shaped (moments,
    master copies) or scalars (step counts); we match by structure where possible and
    by shape as fallback.
    """
    fsdp_size = mesh.shape["fsdp"]
    spec_of = optimizer_state_spec_fn(param_specs, stage, fsdp_size, min_shard_size)

    flat_params, _ = jax.tree_util.tree_flatten(params)
    flat_specs, _ = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    shape_to_spec = {}
    for p, s in zip(flat_params, flat_specs):
        shape_to_spec.setdefault(np.shape(p), s)

    def state_leaf_spec(leaf):
        shape = np.shape(leaf)
        if len(shape) == 0:
            return PartitionSpec()
        if shape in shape_to_spec:
            return spec_of(shape_to_spec[shape], shape)
        # unmatched non-scalar leaf: auto-shard if big (e.g. flattened buffers)
        return param_partition_spec(shape, stage=3 if stage >= 1 else 0,
                                    fsdp_size=fsdp_size, min_shard_size=min_shard_size)

    specs = jax.tree.map(state_leaf_spec, opt_state)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))
