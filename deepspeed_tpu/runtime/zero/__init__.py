"""ZeRO stages as SPMD sharding policies (see ``partition.py``)."""

from deepspeed_tpu.runtime.zero.partition import (build_opt_state_shardings,
                                                  build_param_shardings,
                                                  zero_fsdp_axes,
                                                  zero_placement)

__all__ = ["build_opt_state_shardings", "build_param_shardings",
           "zero_fsdp_axes", "zero_placement"]
