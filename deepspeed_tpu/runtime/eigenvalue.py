"""Hessian max-eigenvalue estimation by power iteration.

Reference analog: ``deepspeed/runtime/eigenvalue.py:13`` (``Eigenvalue`` —
per-block power iteration on the loss curvature, used by the compression
scheduler to order layers by sensitivity).

TPU shape: the reference differentiates twice through torch autograd per block;
here the Hessian-vector product is ``jvp`` of ``grad`` (forward-over-reverse),
jitted once and iterated under ``lax.while_loop`` with the reference's
convergence test (relative eigenvalue change < tol). Blocks are top-level
entries of a params subtree (e.g. ``params["model"]["layer_3"]``) instead of
module scopes.
"""

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class EigenvalueConfig:
    """reference: get_eigenvalue_config (runtime/config.py:565)."""
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "model"
    layer_num: int = 0


def _tree_dot(a, b):
    return sum(jnp.sum(x.astype(jnp.float32) * y.astype(jnp.float32))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _normalize(v, stability):
    norm = jnp.sqrt(_tree_dot(v, v)) + stability
    return jax.tree.map(lambda x: jnp.nan_to_num(x / norm, posinf=0.0,
                                                 neginf=0.0), v)


class Eigenvalue:
    """Power iteration over per-block Hessians (reference Eigenvalue)."""

    def __init__(self, config: Optional[EigenvalueConfig] = None, **kwargs):
        self.cfg = config or EigenvalueConfig(**kwargs)

    def compute_eigenvalue(self, loss_fn: Callable, params: Any,
                           rng: jax.Array) -> Dict[str, float]:
        """loss_fn(params) -> scalar. Returns {block_name: max_eigenvalue}.

        Blocks are resolved from ``cfg.layer_name`` (a '/'-joined path into the
        params tree); each child of that subtree is one block (reference:
        get_layers + layer_num). The HVP holds all other blocks fixed,
        matching the reference's per-block curvature.
        """
        cfg = self.cfg
        node = params
        for part in [p for p in cfg.layer_name.split("/") if p]:
            node = node[part]
        names = sorted(node.keys(), key=_natural_key)
        if cfg.layer_num:
            names = names[:cfg.layer_num]

        results = {}
        for i, name in enumerate(names):
            block = node[name]
            rng, sub = jax.random.split(rng)

            def block_loss(b, name=name):
                patched = dict(node)
                patched[name] = b
                whole = _set_path(params, cfg.layer_name, patched)
                return loss_fn(whole)

            ev = _power_iterate(block_loss, block, sub, cfg.max_iter, cfg.tol,
                                cfg.stability)
            results[name] = float(ev)
            if cfg.verbose:
                log_dist(f"eigenvalue[{name}] = {results[name]:.4e}", ranks=[0])
        # reference post-processing: replace non-positive estimates with the
        # max so ordering degrades gracefully
        max_ev = max([v for v in results.values() if v > 0], default=1.0)
        return {k: (v if v > 0 else max_ev) for k, v in results.items()}


def _natural_key(name: str):
    """layer_2 < layer_10 (lexicographic sort would interleave them and pick
    the wrong blocks for layer_num truncation)."""
    import re
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", name)]


def _set_path(params, path, value):
    parts = [p for p in path.split("/") if p]
    if not parts:
        return value

    def rec(node, parts):
        if len(parts) == 1:
            out = dict(node)
            out[parts[0]] = value
            return out
        out = dict(node)
        out[parts[0]] = rec(node[parts[0]], parts[1:])
        return out
    return rec(params, parts)


def _power_iterate(block_loss, block, rng, max_iter, tol, stability):
    """NOT jit-wrapped: ``block_loss`` is a fresh closure per block per call,
    so a jit static-arg cache would grow without bound and recompile every
    invocation; the ``lax.while_loop`` below compiles its body once per call,
    which is the right cost for an occasional (gas-boundary) computation."""
    grad_fn = jax.grad(block_loss)

    def hvp(v):
        return jax.jvp(grad_fn, (block,), (v,))[1]

    v0 = _normalize(jax.tree.map(
        lambda x: jax.random.normal(rng, x.shape, jnp.float32), block),
        stability)

    def cond(carry):
        i, _, ev, prev = carry
        rel = jnp.abs(ev - prev) / (jnp.abs(ev) + 1e-12)
        return jnp.logical_and(i < max_iter,
                               jnp.logical_or(i < 2, rel > tol))

    def body(carry):
        i, v, ev, _ = carry
        hv = hvp(v)
        new_ev = _tree_dot(v, hv)
        return i + 1, _normalize(hv, stability), new_ev, ev

    _, _, ev, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), v0, jnp.float32(0.0), jnp.float32(1e9)))
    return ev
