"""Domino — tensor-parallel compute/communication overlap.

Reference analog: ``deepspeed/runtime/domino/transformer.py`` (522 LoC,
``DominoTransformerLayer``): each microbatch is split in two along the batch
dim; hand-placed async all-reduce handles (``transformer.py:361-373`` for the
attention row-projection, ``:416-430`` for the MLP row-projection) let the TP
all-reduce of chunk 0 ride under the compute of chunk 1, hiding most of the
two per-layer all-reduces Megatron-style TP pays.

TPU redesign: there are no handles to manage under XLA. We split the tokens
into ``n_chunks`` independent slices; every slice's row-parallel psum is
data-independent of the later slices' matmuls, so XLA's latency-hiding
scheduler (async collectives on ICI) overlaps them exactly where Domino's
``handle.wait()`` placement does — the schedule the reference hand-writes is
recovered by the compiler from a graph that merely *permits* it. The block
below is the same Megatron block the reference wraps (pre-LN -> col/row attn
-> residual -> pre-LN -> col/row MLP -> residual) built on the AutoTP parallel
layers, with the chunk boundary carried across the attention->MLP seam the way
Domino interleaves its two microbatches.
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.module_inject.layers import (
    ColumnParallelLinear, RowParallelLinear)


def chunk_tokens(x: jnp.ndarray, n_chunks: int, axis: int = 0):
    """Split activations into ``n_chunks`` equal slices along ``axis``
    (reference splits the batch dim in two, ``transformer.py:338``)."""
    if x.shape[axis] % n_chunks:
        raise ValueError(
            f"domino: dim {axis} of size {x.shape[axis]} not divisible by "
            f"n_chunks={n_chunks}")
    return jnp.split(x, n_chunks, axis=axis)


class _DominoAttention(nn.Module):
    """Column-parallel QKV + row-parallel output projection. The psum implied
    by the row projection is the collective Domino overlaps (reference
    ``transformer.py:361``)."""

    num_heads: int
    head_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        h, d = self.num_heads, self.head_dim
        qkv = ColumnParallelLinear(3 * h * d, use_bias=False, dtype=self.dtype,
                                   name="qkv")(x)
        q, k, v = jnp.split(qkv.reshape(b, s, 3 * h, d), 3, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d).astype(x.dtype)
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, h * d)
        return RowParallelLinear(x.shape[-1], use_bias=False, dtype=self.dtype,
                                 name="out")(ctx)


class _DominoMLP(nn.Module):
    """Column-parallel up + row-parallel down projection (reference
    ``transformer.py:416`` overlaps the down-projection all-reduce)."""

    intermediate: int
    dtype: Any = jnp.bfloat16
    act: Callable = nn.gelu

    @nn.compact
    def __call__(self, x):
        y = ColumnParallelLinear(self.intermediate, use_bias=False,
                                 dtype=self.dtype, name="up")(x)
        y = self.act(y)
        return RowParallelLinear(x.shape[-1], use_bias=False, dtype=self.dtype,
                                 name="down")(y)


class DominoTransformerLayer(nn.Module):
    """Megatron TP transformer block with Domino chunked comm/compute overlap.

    ``n_chunks=1`` is the plain (non-overlapped) block; ``n_chunks=2`` matches
    the reference's two-microbatch interleave. Chunks are split along the batch
    dim, flow through attention and MLP independently (so their row-parallel
    psums are independent collectives XLA can overlap with the sibling chunks'
    matmuls), and are concatenated only at the layer output — the chunk seam is
    carried across the attention->MLP boundary like the reference's
    ``DominoTransformerLayer.forward``.
    """

    num_heads: int
    head_dim: int
    intermediate: int
    n_chunks: int = 2
    dtype: Any = jnp.bfloat16
    ln_eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        attn = _DominoAttention(self.num_heads, self.head_dim, dtype=self.dtype,
                                name="attn")
        mlp = _DominoMLP(self.intermediate, dtype=self.dtype, name="mlp")
        ln1 = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype, name="ln1")
        ln2 = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype, name="ln2")

        chunks = chunk_tokens(x, self.n_chunks, axis=0)
        # Stage 1: per-chunk attention. Chunk i's row-psum overlaps chunk i+1's
        # matmuls (no data dependency between them).
        after_attn = [c + attn(ln1(c)) for c in chunks]
        # Stage 2: per-chunk MLP. The last chunk's attention psum overlaps the
        # first chunk's MLP compute — the cross-boundary interleave that is
        # Domino's main win (reference transformer.py:373-416).
        out = [a + mlp(ln2(a)) for a in after_attn]
        return jnp.concatenate(out, axis=0)


def domino_overlap(fn: Callable, n_chunks: int = 2, axis: int = 0) -> Callable:
    """Wrap any token-wise ``fn(x) -> y`` so it runs per-chunk; use for custom
    blocks that end in a row-parallel reduce. The returned function yields
    bit-identical results to ``fn`` for token-independent ``fn`` while exposing
    ``n_chunks`` independent collectives to the scheduler."""

    def wrapped(x):
        return jnp.concatenate([fn(c) for c in chunk_tokens(x, n_chunks, axis)],
                               axis=axis)

    return wrapped
