"""sched — the shared host-orchestration core both loops consume.

PR 3 grew the training engine an async step pipeline (deferred metric
readback through a device-side ring, ONE designated batched ``device_get``
drain, staged prefetch); ROADMAP item 1 asks the serve loop to run on the
same machinery instead of growing a parallel copy. This module is that
extraction: the engine-agnostic host-orchestration primitives, consumed by
``runtime/engine.py`` (train) and ``inference/v2/engine_v2.py`` +
``serving/server.py`` (serve).

Three pieces, all DS002-registered hot paths (tools/dslint/hotpath.py):

* ``DispatchRing`` — the dispatch ring: device-side pending payloads, the
  bounded host-entry queue consumers replay from, and ``drain()`` — THE
  designated readback point. One batched ``jax.device_get`` moves every
  pending payload to host (and, by data dependency, proves the queued
  device work completed — the anchor that keeps reconciled timers
  honest). Nothing else in a hot loop may call ``.device_get``.
* ``StagedPrefetcher`` — identity-keyed staged-prefetch lifecycle: one
  background loader per source iterator, loud (then throttled) warnings
  when iterator churn defeats the staging.
* ``TickLedger`` — the serve tick's deterministic scheduler counters:
  per-tick prefill-token caps, decode-stall tokens, chunk conservation.
  On a CPU container wall-clock is noise; these counters are the proof
  set the decode-first chunked-prefill scheduler is judged by
  (``dstpu_bench_serve`` ``report["scheduler"]``).

Host-side bookkeeping only: no jit, no collectives, no per-step
allocation beyond the payload dicts the caller already built.
"""

import collections
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax

from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger


class DrainResult(NamedTuple):
    """One ``DispatchRing.drain()``: host payloads + the extra operand that
    rode the same transfer, and the window the drained steps span."""
    payloads: List[Dict[str, Any]]
    extra: Any
    window_s: float        # seconds since the window anchor (0.0 unanchored)
    anchored: bool


class DispatchRing:
    """Device-side pending payload ring + bounded host-entry queue + THE
    designated drain ``device_get``.

    The producer pushes payload dicts whose values may be live device
    arrays (fresh jit outputs — never donated buffers: donation deletes
    them while they'd still sit in the ring). ``drain`` moves everything
    across in one batched transfer, computes the reconciliation window
    from the anchor the producer set at the window's first dispatch, and
    leaves host fan-out to the caller. Drained entries the caller stores
    land in a bounded deque consumers ``take()``/``requeue()`` from —
    overflow is never silent.
    """

    def __init__(self, capacity: int = 4096, sync_every: int = 1,
                 span_name: str = "engine/drain", span_cat: str = "train",
                 name: str = "async_pipeline"):
        self.pending: List[Dict[str, Any]] = []    # device-side payloads
        self.drained: collections.deque = collections.deque(maxlen=capacity)
        self.sync_every = int(sync_every)
        self.span_name = span_name
        self.span_cat = span_cat
        self.name = name
        self.anchor: Optional[float] = None        # window start (time.time)

    def __len__(self) -> int:
        return len(self.pending)

    def push(self, payload: Dict[str, Any]) -> bool:
        """Queue one step's device-side payload; returns True when the
        ring reached the drain cadence (caller runs its drain)."""
        self.pending.append(payload)
        return len(self.pending) >= self.sync_every

    def rearm_if_idle(self) -> None:
        """Anchor a fresh window at this dispatch iff the ring is empty —
        host pauses between windows (checkpoint I/O, idle gaps after a
        flush) must never be booked as step time at the next drain."""
        if not self.pending:
            self.anchor = time.time()

    def reset_anchor(self) -> None:
        self.anchor = None

    def drain(self, extra: Any = None,
              on_error: Optional[Callable[[BaseException], None]] = None
              ) -> Optional[DrainResult]:
        """THE designated readback point: one batched ``device_get`` over
        every pending payload (+ ``extra``, which rides the same
        transfer). Returns None when nothing is pending. ``on_error``
        sees a raising transfer before the exception unwinds (the
        execution-time-OOM classify-and-stash contract)."""
        if not self.pending:
            return None
        ring, self.pending = self.pending, []
        try:
            with get_tracer().span(self.span_name, cat=self.span_cat,
                                   steps=len(ring)):
                host, extra_host = jax.device_get((ring, extra))
        except Exception as e:
            if on_error is not None:
                on_error(e)
            raise
        window, anchored = 0.0, self.anchor is not None
        if anchored:
            window = max(time.time() - self.anchor, 0.0)
        return DrainResult(payloads=host, extra=extra_host,
                           window_s=window, anchored=anchored)

    def store(self, entries: List[Dict[str, Any]]) -> int:
        """Append drained host entries to the consumer queue; returns the
        number of oldest un-consumed entries the bounded deque evicted
        (warned — with no consumer attached the bounded-lag guard
        guarantee degrades past this point)."""
        dropped = len(self.drained) + len(entries) - self.drained.maxlen
        if dropped > 0:
            logger.warning(
                "%s: drained-metrics queue overflow — %d oldest "
                "un-consumed entries dropped (no take_drained_metrics "
                "consumer attached?)", self.name, dropped)
        self.drained.extend(entries)
        return max(dropped, 0)

    def take(self) -> List[Dict[str, Any]]:
        """Pop every drained-but-unconsumed host entry, in order."""
        out = list(self.drained)
        self.drained.clear()
        return out

    def requeue(self, entries: List[Dict[str, Any]]) -> None:
        """Put taken-but-unprocessed entries back at the FRONT (original
        order preserved); refuses to evict newer entries silently."""
        free = self.drained.maxlen - len(self.drained)
        if len(entries) > free:
            # appendleft on a full deque would evict the NEWEST entries
            # from the right — refuse to lose them silently
            logger.warning(
                "%s: requeue overflow — %d newest entries dropped from "
                "the drained-metrics queue", self.name, len(entries) - free)
            entries = entries[:free]
        for e in reversed(entries):
            self.drained.appendleft(e)


class StagedPrefetcher:
    """Identity-keyed staged-prefetch lifecycle: one loader per source
    iterator. A new source closes the old loader (dropping its staged
    batches — the source iterator has already advanced past them), loud
    the first few switches and throttled after."""

    def __init__(self, depth: int = 2, name: str = "async_pipeline"):
        self.depth = int(depth)
        self.name = name
        self.loader = None
        self.source = None
        self.switches = 0

    def ensure(self, source, factory: Callable[[], Any]):
        """Return the live loader for ``source``, building one via
        ``factory`` when the source identity changed (or none exists)."""
        if self.loader is not None and self.source is source:
            return self.loader
        if self.loader is not None:
            self.switches += 1
            if self.switches <= 3 or self.switches % 100 == 0:
                # a fresh iterator object per call defeats prefetch (thread
                # churn + staged batches already pulled from the source are
                # dropped) — loud the first few times, throttled after
                logger.warning(
                    "%s: data_iter identity changed (switch #%d) — "
                    "discarding the previous prefetcher and up to %d "
                    "staged batches; pass a STABLE iterator across "
                    "train_batch calls", self.name, self.switches,
                    self.depth)
            self.loader.close()
        self.loader = factory()
        self.source = source
        return self.loader

    def close(self) -> None:
        if self.loader is not None:
            self.loader.close()
            self.loader = None
            self.source = None


class TickLedger:
    """Deterministic per-tick serve-scheduler counters — the chunked
    prefill proof set. ``observe_tick`` is called once per engine step
    with that tick's planned work; everything else is host int
    arithmetic (no clocks, so the counters are identical across hosts
    for the same seeded workload).

    Window semantics: warmed bench runs call ``reset_window()`` at the
    measurement mark so the warm wave's ticks never leak into the
    measured maxima; cumulative totals keep running (every proof
    identity over them is conservation-shaped)."""

    #: bounded per-request attribution table (serving runs indefinitely;
    #: finished requests are popped, abandoned ones age out FIFO)
    REQUEST_CAP = 4096

    def __init__(self):
        self.ticks = 0                    # observed (working) ticks
        self.prefill_ticks = 0            # ticks that ran >= 1 chunk
        self.decode_ticks = 0             # ticks that ran a decode batch
        self.chunk_tokens_total = 0       # prefill tokens through chunks
        self.chunks_total = 0
        self.decode_tokens_total = 0
        self.capped_chunk_ticks = 0       # prefill ticks bound by the cap
        # uid -> {"ticks", "prefill_tokens", "chunks", "decode_tokens"}:
        # which slice of the tick stream each request consumed — the
        # wall-clock-free denominator the SLO layer states latencies in
        # (ceil-div cap units via ``units()``)
        self.request_ticks: Dict[int, Dict[str, int]] = {}
        self.reset_window()

    @staticmethod
    def units(tokens: int, unit_tokens: int) -> int:
        """Ceil-div of a token count into ``unit_tokens``-sized scheduling
        quanta — the ``max_decode_gap_ticks`` normalizer, exposed so the
        SLO histograms can be fed in cap units instead of wall seconds
        (deterministic across hosts; 0 when either operand is)."""
        if unit_tokens <= 0 or tokens <= 0:
            return 0
        return -(-int(tokens) // int(unit_tokens))    # ceil div

    def reset_window(self) -> None:
        """Start the measured window: maxima reset, totals keep running."""
        self.max_prefill_tokens_per_tick = 0
        # prefill tokens in the worst tick that ALSO ran decodes — the
        # exact "tokens of prefill a decode token waited behind" measure
        self.max_decode_stall_tokens = 0
        self.window_prefill_ticks = 0
        self.window_chunk_tokens = 0

    def observe_tick(self, prefill_tokens: int, chunks: int,
                     decode_tokens: int, cap: int = 0) -> None:
        self.ticks += 1
        if chunks:
            self.prefill_ticks += 1
            self.window_prefill_ticks += 1
            self.chunks_total += chunks
            self.chunk_tokens_total += prefill_tokens
            self.window_chunk_tokens += prefill_tokens
            if cap > 0 and prefill_tokens >= cap:
                self.capped_chunk_ticks += 1
        if decode_tokens:
            self.decode_ticks += 1
            self.decode_tokens_total += decode_tokens
        if prefill_tokens > self.max_prefill_tokens_per_tick:
            self.max_prefill_tokens_per_tick = prefill_tokens
        if decode_tokens and prefill_tokens > self.max_decode_stall_tokens:
            self.max_decode_stall_tokens = prefill_tokens

    def attribute_request(self, uid: int, prefill_tokens: int = 0,
                          chunks: int = 0, decode_tokens: int = 0) -> None:
        """Book one tick's work against the request that consumed it.
        Called alongside ``observe_tick`` by callers that know the
        per-request split (the serve loop's fan-out does); pure host int
        arithmetic like everything else here."""
        entry = self.request_ticks.get(uid)
        if entry is None:
            while len(self.request_ticks) >= self.REQUEST_CAP:
                # FIFO age-out: dict preserves insertion order
                self.request_ticks.pop(next(iter(self.request_ticks)))
            entry = {"ticks": 0, "prefill_tokens": 0, "chunks": 0,
                     "decode_tokens": 0}
            self.request_ticks[uid] = entry
        entry["ticks"] += 1
        entry["prefill_tokens"] += int(prefill_tokens)
        entry["chunks"] += int(chunks)
        entry["decode_tokens"] += int(decode_tokens)

    def pop_request(self, uid: int) -> Optional[Dict[str, int]]:
        """Remove and return a finished request's attribution entry (None
        when the request was never attributed or already aged out)."""
        return self.request_ticks.pop(uid, None)

    def merge_from(self, other: "TickLedger") -> None:
        """Fold another ledger in (the disaggregated pair sums its role
        engines' ledgers into one proof set)."""
        self.ticks += other.ticks
        self.prefill_ticks += other.prefill_ticks
        self.decode_ticks += other.decode_ticks
        self.chunk_tokens_total += other.chunk_tokens_total
        self.chunks_total += other.chunks_total
        self.decode_tokens_total += other.decode_tokens_total
        self.capped_chunk_ticks += other.capped_chunk_ticks
        self.window_prefill_ticks += other.window_prefill_ticks
        self.window_chunk_tokens += other.window_chunk_tokens
        self.max_prefill_tokens_per_tick = max(
            self.max_prefill_tokens_per_tick,
            other.max_prefill_tokens_per_tick)
        self.max_decode_stall_tokens = max(
            self.max_decode_stall_tokens, other.max_decode_stall_tokens)

    def snapshot(self, cap: int = 0, gap_unit_tokens: int = 0
                 ) -> Dict[str, Any]:
        """The scheduler proof set. ``max_decode_gap_ticks`` states the
        worst decode stall in cap-sized scheduling ticks: how many
        chunk-cap quanta of prefill a decode token waited behind in the
        worst tick (1 == decode never waited more than one chunk —
        "never serialized behind a full prefill"). ``gap_unit_tokens``
        overrides the normalizer so an uncapped baseline run can be
        stated in the SAME units as the capped run it is compared to."""
        unit = int(gap_unit_tokens or cap or 0)
        gap = self.units(self.max_decode_stall_tokens, unit)
        util = 0.0
        if cap > 0 and self.window_prefill_ticks > 0:
            util = self.window_chunk_tokens / float(
                cap * self.window_prefill_ticks)
        return {
            "prefill_chunk_tokens": int(cap),
            "ticks": self.ticks,
            "prefill_ticks": self.prefill_ticks,
            "decode_ticks": self.decode_ticks,
            "chunks_total": self.chunks_total,
            "chunk_tokens_total": self.chunk_tokens_total,
            "decode_tokens_total": self.decode_tokens_total,
            "capped_chunk_ticks": self.capped_chunk_ticks,
            "max_prefill_tokens_per_tick": self.max_prefill_tokens_per_tick,
            "max_decode_stall_tokens": self.max_decode_stall_tokens,
            "decode_gap_unit_tokens": unit,
            "max_decode_gap_ticks": gap,
            "prefill_cap_utilization": round(util, 4),
        }
