"""Compressed sparse (row-indexed) tensor + sparse gradient collectives.

Reference analog: ``deepspeed/runtime/sparse_tensor.py`` (``SparseTensor``, the
IndexedSlices-style container for sparse embedding gradients) and the engine's
sparse allreduce (``runtime/engine.py:2518-2588 sparse_allreduce_bucket`` —
all_gather of indices and values; the sum stays implicit in the concatenated
representation until densification).

TPU shape: a registered pytree of (indices [K], values [K, D], dense rows N).
``from_dense`` keeps the top-k rows by norm (static K — jit needs fixed
shapes; the reference uses dynamic nonzero rows, which XLA cannot).
``sparse_all_gather`` concatenates every rank's (indices, values) over a mesh
axis inside shard_map — wire traffic is O(K·D·world) instead of O(N·D) when
K ≪ N, exactly the reference's win for embedding gradients.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """indices: [K] int32 row ids; values: [K, D]; dense_rows: static N."""

    def __init__(self, indices, values, dense_rows: int):
        self.indices = indices
        self.values = values
        self.dense_rows = int(dense_rows)

    def tree_flatten(self):
        return (self.indices, self.values), self.dense_rows

    @classmethod
    def tree_unflatten(cls, dense_rows, children):
        return cls(children[0], children[1], dense_rows)

    # ------------------------------------------------------------------
    @classmethod
    def from_dense(cls, dense, k: int) -> "SparseTensor":
        """Keep the k rows with largest L2 norm (static-k analog of the
        reference's nonzero-row selection)."""
        norms = jnp.sum(jnp.square(dense.astype(jnp.float32)), axis=-1)
        _, idx = jax.lax.top_k(norms, k)
        idx = idx.astype(jnp.int32)
        return cls(idx, jnp.take(dense, idx, axis=0), dense.shape[0])

    def to_dense(self):
        out = jnp.zeros((self.dense_rows, self.values.shape[-1]),
                        self.values.dtype)
        return out.at[self.indices].add(self.values)

    def add(self, other: "SparseTensor") -> "SparseTensor":
        assert self.dense_rows == other.dense_rows
        return SparseTensor(jnp.concatenate([self.indices, other.indices]),
                            jnp.concatenate([self.values, other.values]),
                            self.dense_rows)

    def sparse_size(self) -> Tuple[int, int]:
        return (self.indices.size + self.values.size,
                self.dense_rows * self.values.shape[-1])


def _nbytes(x) -> int:
    return int(jnp.size(x)) * jnp.dtype(x.dtype).itemsize


def sparse_all_gather(st: SparseTensor, axis_name: str,
                      logical_bytes: int = None) -> SparseTensor:
    """The reference's sparse allreduce: gather all ranks' (indices, values);
    duplicates stay un-summed until ``to_dense`` scatter-adds them. Usable
    inside shard_map.

    Facade-recorded like every collective (comm guard ``_record`` sees the
    op; dstrace gets a comm instant): ``bytes`` is the logical payload —
    the dense tensor a full-precision reduction would have moved, passed by
    the caller (defaults to the sparse representation itself when gathering
    genuinely sparse data) — and ``wire_bytes`` the (indices, values) pair
    actually on the wire, so the sparse path's compression ratio shows up
    in the same counters as the quantized collectives'."""
    wire = _nbytes(st.indices) + _nbytes(st.values)
    from deepspeed_tpu.comm.comm import _record
    _record("sparse_all_gather", st.values, axis_name,
            nbytes=wire if logical_bytes is None else int(logical_bytes),
            wire_bytes=wire, kind="all_gather")
    idx = jax.lax.all_gather(st.indices, axis_name, axis=0, tiled=True)
    vals = jax.lax.all_gather(st.values, axis_name, axis=0, tiled=True)
    return SparseTensor(idx, vals, st.dense_rows)


def sparse_grad_sync(g, axes, k: int):
    """Mean-reduce an embedding-style gradient leaf over the manual ``axes``
    with the sparse wire format (the engine path of the reference's
    ``sparse_allreduce_bucket``, engine.py:2518): each device keeps its top-k
    rows by norm — exact when ``k`` ≥ the device's batch-token count, since a
    pure-lookup embedding gradient touches at most one row per token — then
    (indices, values) all_gather per axis and a scatter-add densify. Wire
    bytes: O(k·D·world) vs O(N·D) dense. Must run inside a shard_map whose
    manual axes include ``axes``."""
    st = SparseTensor.from_dense(g, k)
    dense_bytes = _nbytes(g)
    w = 1
    for ax in axes:
        w *= jax.lax.axis_size(ax)
        # logical payload per hop = the dense gradient a full-precision
        # reduction over this axis would move; wire = (indices, values)
        st = sparse_all_gather(st, ax, logical_bytes=dense_bytes)
    return (st.to_dense() / w).astype(g.dtype)
