"""ZeRO-Infinity training-side PARAMETER offload: train models whose compute
weights exceed device HBM on a small slice.

Reference analogs:
- ``runtime/swap_tensor/partitioned_param_swapper.py:37``
  (AsyncPartitionedParameterSwapper): fp16 params on NVMe, fetched into pinned
  buffers around each module's fwd/bwd, wired via
  ``partition_parameters.py:1100`` and ``parameter_offload.py:85`` module hooks.
- ``pipelined_optimizer_swapper.py``: double-buffered swap (prefetch sub-group
  *i+1* while sub-group *i* computes).

TPU-native shape: instead of per-``nn.Module`` hooks patched into a mutable
module tree, the model is partitioned into LAYER GROUPS (embed | N transformer
blocks per group | final-norm+head) and the train step becomes a host-driven
stream over jitted per-group functions:

  fwd:  for g in 0..G-1:   H2D(params[g+1]) overlaps  x = fwd_g(params[g], x)
        (boundary activations x_g stay in HBM — [B,S,H] each, tiny next to
        the weights being streamed)
  loss: tail_grad() returns (loss, dx, tail grads) in one jit
  bwd:  for g in G-1..0:   H2D(params[g-1]) overlaps
        (dx, grads_g) = bwd_g(params[g], x_g, dx)   # recompute-in-group (remat)
        grads_g stream D2H into fp32 host accumulators and leave HBM
  step: fused C++ host optimizer (CPUAdam/Adagrad/Lion) updates fp32 masters
        (``HostOffloadOptimizer`` — host or NVMe moment tier), then the
        compute-dtype store is refreshed from the masters.

Peak HBM = 2 layer groups (double buffer) + boundary activations + one group's
grads — independent of model size. ``offload_param.device: cpu`` keeps the
compute-dtype store in host RAM; ``nvme`` keeps layer groups in per-group files
streamed through the aio engine (embed/tail stay in RAM: they are touched
twice per microbatch). ``offload_param.ratio`` (Twin-Flow, reference
engine.py:757) pins the first ``1-ratio`` fraction of layer groups in RAM.

Supported model family: the in-repo Llama tree layout (``model/embed``,
``model/layer_i``, ``model/final_norm``[, ``model/lm_head``]) with
``scan_layers=False`` — the same layout the ZeRO-Inference streamed path uses.
Unsupported configs RAISE at engine init (a parsed-and-ignored ``offload_param``
was the round-4 correctness trap).
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.ops.cpu_adam import to_bf16
from deepspeed_tpu.runtime.offload import HostOffloadOptimizer
from deepspeed_tpu.utils.logging import log_dist


def validate_param_offload(config: DeepSpeedTPUConfig, model) -> None:
    """Raise (never silently ignore) when ``offload_param`` cannot be honored."""
    pcfg = config.zero_config.offload_param
    if pcfg.device not in ("cpu", "nvme"):
        raise ValueError(
            f"offload_param.device must be none|cpu|nvme, got {pcfg.device!r}")
    cfg = getattr(model, "cfg", None)
    if cfg is not None and hasattr(cfg, "base") and hasattr(cfg, "moe"):
        cfg = cfg.base          # MixtralConfig wraps a LlamaConfig
    if cfg is None or not hasattr(cfg, "num_layers"):
        raise ValueError(
            "offload_param needs a layered model exposing .cfg.num_layers "
            "(the in-repo Llama/Mixtral families); got "
            f"{type(model).__name__} — either drop offload_param or use a "
            "LlamaForCausalLM-style model")
    if getattr(cfg, "scan_layers", False):
        raise ValueError(
            "offload_param requires scan_layers=False: layer streaming "
            "addresses per-layer subtrees (model/layer_i), which nn.scan "
            "stacks into one leaf")
    if config.fp16.enabled:
        raise ValueError(
            "offload_param supports bf16/fp32 only (TPU-native precisions); "
            "fp16 dynamic loss scaling is not wired through the streamed "
            "step — use bf16")
    if config.compression_config or config.eigenvalue.enabled:
        raise ValueError(
            "offload_param is incompatible with compression/eigenvalue "
            "(both address device-resident params)")
    if config.sparse_gradients_enabled:
        raise ValueError(
            "offload_param accumulates grads on host; sparse_gradients' "
            "wire reduction does not apply — disable it")
    if config.flops_profiler.enabled:
        raise ValueError(
            "offload_param is incompatible with flops_profiler (it traces "
            "the whole-model step, which never exists under streaming)")
    zc = config.zero_config
    if (zc.zero_hpz_partition_size or 1) > 1 or (zc.mics_shard_size or 0) > 0 \
            or zc.zero_quantized_weights or zc.zero_quantized_gradients:
        raise ValueError(
            "offload_param is incompatible with hpZ/MiCS/qwZ/qgZ: those "
            "shard or compress device-resident params; offloaded params "
            "stream from host instead")
    if pcfg.device == "nvme" and not pcfg.nvme_path:
        raise ValueError("offload_param.device=nvme requires nvme_path")


class _BlockStack(nn.Module):
    """``n`` LlamaBlocks under local names layer_0..layer_{n-1} (the group's
    host subtree is re-keyed from global layer indices)."""
    cfg: Any
    n: int

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        from deepspeed_tpu.models.llama import REMAT_POLICIES, LlamaBlock
        block_cls = LlamaBlock
        if self.cfg.remat:
            block_cls = nn.remat(LlamaBlock,
                                 policy=REMAT_POLICIES[self.cfg.remat_policy],
                                 prevent_cse=True, static_argnums=())
        for i in range(self.n):
            x = block_cls(self.cfg, name=f"layer_{i}")(x, positions, segment_ids)
        return x


class _MoEBlockStack(nn.Module):
    """``n`` MixtralBlocks; returns (x, sum of the groups' MoE aux losses).
    The aux sum streams through the fwd carry and its unit cotangent seeds
    every group's backward (each block's gating contributes to the loss)."""
    cfg: Any                     # MixtralConfig
    n: int

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        from deepspeed_tpu.models.llama import REMAT_POLICIES
        from deepspeed_tpu.models.mixtral import MixtralBlock
        block_cls = MixtralBlock
        if self.cfg.base.remat:
            block_cls = nn.remat(
                MixtralBlock,
                policy=REMAT_POLICIES[self.cfg.base.remat_policy],
                prevent_cse=True, static_argnums=())
        aux = jnp.float32(0.0)
        for i in range(self.n):
            x, a = block_cls(self.cfg, name=f"layer_{i}")(x, positions)
            aux = aux + a
        return x, aux


class _TailLoss(nn.Module):
    """final_norm + unembed + masked mean CE over all S positions (labels are
    pre-shifted/padded host-side so shapes stay static — same formulation as
    LlamaForCausalLM._chunked_loss, numerically equal to the dense loss).
    ``head_dtype`` overrides the unembed matmul dtype (Mixtral's lm_head is
    a plain fp32 Dense while its norm stays in the compute dtype)."""
    cfg: Any
    head_dtype: Any = None

    @nn.compact
    def __call__(self, x, embedding, labels, mask):
        from deepspeed_tpu.models.llama import LMHead, RMSNorm, softcap_logits
        cfg = self.cfg
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    scale_offset=cfg.rms_scale_offset, name="final_norm")(x)
        if cfg.tie_embeddings:
            # flax Embed.attend: promote both to cfg.dtype, contract over H
            logits = jnp.dot(x.astype(cfg.dtype),
                             embedding.astype(cfg.dtype).T)
        else:
            logits = LMHead(cfg.hidden_size, cfg.vocab_size,
                            self.head_dtype or cfg.dtype, name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        logits = softcap_logits(logits, cfg.logits_soft_cap)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        m = mask.astype(jnp.float32)
        return -jnp.sum(ll * m) / jnp.maximum(jnp.sum(m), 1.0)


def _path_str(path) -> str:
    return "/".join(getattr(e, "key", getattr(e, "name", str(e)))
                    for e in path)


class ParamOffloadTrainer:
    """Streamed train step over a host-resident parameter store."""

    def __init__(self, model, config: DeepSpeedTPUConfig, params_host,
                 mesh, batch_sharding, lr_schedule, tensor_rules=None):
        validate_param_offload(config, model)
        # Mixtral (MoE) wraps a LlamaConfig and keeps its param tree at top
        # level (no "model/" prefix); blocks return (x, aux_loss)
        self._moe = hasattr(model.cfg, "base") and hasattr(model.cfg, "moe")
        self._model_cfg = model.cfg
        self.cfg = model.cfg.base if self._moe else model.cfg
        self._prefix = "" if self._moe else "model/"
        self.config = config
        self.mesh = mesh
        self.batch_sharding = batch_sharding
        self.lr_schedule = lr_schedule
        self.compute_dtype = config.precision_dtype
        self._tensor_rules = tensor_rules
        pcfg = config.zero_config.offload_param

        # --- flat host masters + fused host optimizer -----------------------
        # offload_param implies host masters+moments: if weights don't fit HBM,
        # fp32 states certainly don't. offload_optimizer.device selects the
        # moment tier (cpu default; nvme = full ZeRO-Infinity).
        ocfg = config.zero_config.offload_optimizer
        if ocfg.device == "none":
            ocfg = ocfg.model_copy(update={"device": "cpu"})
            log_dist("offload_param: optimizer states implicitly offloaded "
                     "to cpu (device weights are streamed; fp32 states "
                     "cannot be device-resident)", ranks=[0])
        flat, self._treedef = jax.tree_util.tree_flatten(params_host)
        paths = jax.tree_util.tree_flatten_with_path(params_host)[0]
        self._paths = [_path_str(p) for p, _ in paths]
        self._path_idx = {p: i for i, p in enumerate(self._paths)}
        host_leaves = [np.asarray(x, np.float32) for x in flat]
        opt_type = config.optimizer.type if config.optimizer else "adamw"
        opt_params = dict(config.optimizer.params) if config.optimizer else {}
        self.opt = HostOffloadOptimizer(host_leaves, opt_type, opt_params, ocfg)

        # --- compute-dtype store (the streamed weights) ---------------------
        self._store: List[np.ndarray] = [None] * len(host_leaves)
        self._refresh_store()

        # --- layer groups ----------------------------------------------------
        L = self.cfg.num_layers
        per = max(1, int(getattr(pcfg, "layers_per_group", 1) or 1))
        self._layer_groups: List[List[int]] = [
            list(range(a, min(a + per, L))) for a in range(0, L, per)]
        pre = self._prefix
        self._embed_idx = self._subtree_idx([("embed", pre + "embed")])
        tail_map = [("final_norm", pre + "final_norm")]
        if not self.cfg.tie_embeddings:
            tail_map.append(("lm_head", pre + "lm_head"))
        self._tail_idx = self._subtree_idx(tail_map)
        self._group_idx: List[Any] = [
            self._subtree_idx([(f"layer_{j}", pre + f"layer_{i}")
                               for j, i in enumerate(g)])
            for g in self._layer_groups]

        # --- NVMe tier for layer groups --------------------------------------
        self._nvme = pcfg.device == "nvme"
        self._nvme_groups: List[bool] = [False] * len(self._layer_groups)
        if self._nvme:
            from deepspeed_tpu.ops.async_io import AsyncIOHandle
            self._aio = AsyncIOHandle(num_threads=max(2, pcfg.buffer_count))
            self._nvme_dir = os.path.join(
                pcfg.nvme_path, f"params_proc{jax.process_index()}")
            os.makedirs(self._nvme_dir, exist_ok=True)
            G = len(self._layer_groups)
            # Twin-Flow: first (1-ratio) fraction of groups pinned in RAM
            self._nvme_groups = [gi >= (1.0 - pcfg.ratio) * G for gi in range(G)]
            self._bufs = [np.empty(max(self._group_nbytes(gi)
                                       for gi in range(G)), np.uint8)
                          for _ in range(2)]
            self._buf_group = [None, None]     # which group each buffer holds
            self._pending_req: Dict[int, Tuple[int, int]] = {}
            # initial param files; RAM copies of nvme groups drop (masters
            # remain authoritative)
            self._writeback_nvme()

        self._replicated = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        # TP-sharded streaming: when tensor_rules are given, each streamed
        # leaf lands on device already sharded over the mesh's tensor axes —
        # 1/tp of the H2D bytes and HBM per chip vs replicated streaming
        # (AutoTP composed with ZeRO-Infinity). Axes absent from the mesh
        # are filtered out of the rule's spec (same policy as
        # shard_activation).
        self._leaf_sharding: List[Any] = [self._replicated] * len(host_leaves)
        if tensor_rules is not None:
            from jax.tree_util import DictKey
            axes = set(mesh.shape)
            def keep(entry):
                if isinstance(entry, (tuple, list)):
                    sub = tuple(a for a in entry if a in axes)
                    return sub if sub else None
                return entry if entry in axes else None

            for i, p in enumerate(self._paths):
                spec = tensor_rules(
                    tuple(DictKey(part) for part in p.split("/")),
                    jax.ShapeDtypeStruct(self.opt.leaf_shapes()[i],
                                         jnp.float32))
                if spec is None:
                    continue
                shape = self.opt.leaf_shapes()[i]
                kept = []
                for d, e in enumerate(tuple(spec)):
                    e = keep(e)
                    if e is not None:
                        size = int(np.prod([mesh.shape[a] for a in
                                            (e if isinstance(e, tuple)
                                             else (e,))]))
                        if d >= len(shape) or shape[d] % size:
                            e = None     # indivisible dim: replicate it
                    kept.append(e)
                if any(e is not None for e in kept):
                    self._leaf_sharding[i] = jax.sharding.NamedSharding(
                        mesh, jax.sharding.PartitionSpec(*kept))
        self._accum: List[Optional[np.ndarray]] = [None] * len(host_leaves)
        self._stack_fwd: Dict[int, Any] = {}
        self._stack_bwd: Dict[int, Any] = {}
        self._embed_fwd_fn = None
        self._embed_bwd_fn = None
        self._tail_fn = None
        self.bytes_streamed = 0            # per-step H2D stream volume
        self.phase_seconds: Dict[str, float] = {}
        self.skipped_steps = 0
        log_dist(
            f"param offload: device={pcfg.device} groups={len(self._layer_groups)}"
            f" x{per} layers, store="
            f"{sum(s.nbytes for s in self._store if s is not None) / 1e6:.0f}MB"
            " RAM" + (f" + nvme@{self._nvme_dir}" if self._nvme else ""),
            ranks=[0])

    # --- host store plumbing -------------------------------------------------
    def _subtree_idx(self, name_map: List[Tuple[str, str]]):
        """Local-name tree of GLOBAL flat-leaf indices for one group."""
        tree = {}
        for local, global_prefix in name_map:
            sub = {}
            for p, i in self._path_idx.items():
                if p == global_prefix or p.startswith(global_prefix + "/"):
                    rel = p[len(global_prefix) + 1:] if p != global_prefix else ""
                    node = sub
                    parts = rel.split("/") if rel else []
                    for k in parts[:-1]:
                        node = node.setdefault(k, {})
                    if parts:
                        node[parts[-1]] = i
                    else:
                        sub = i
            if sub == {}:
                raise ValueError(
                    f"offload_param: param subtree {global_prefix!r} not found "
                    "(expected the Llama tree layout model/embed, "
                    "model/layer_i, model/final_norm[, model/lm_head])")
            tree[local] = sub
        return tree

    def _refresh_store(self):
        """Compute-dtype store <- fp32 masters (after each optimizer step).
        Streams one master at a time so NVMe-swapped masters never all
        materialize in RAM."""
        cast = to_bf16 if self.compute_dtype == jnp.bfloat16 else \
            (lambda a: np.asarray(a, np.dtype(self.compute_dtype)))
        for i, m in self.opt.iter_masters():
            self._store[i] = cast(m)

    def _group_file(self, gi: int) -> str:
        return os.path.join(self._nvme_dir, f"group{gi}.bin")

    def _write_group_file(self, gi: int):
        idxs = jax.tree_util.tree_leaves(self._group_idx[gi])
        blob = np.concatenate([
            np.ascontiguousarray(self._store[i]).view(np.uint8).ravel()
            for i in idxs])
        self._group_blobs = getattr(self, "_group_blobs", {})
        self._group_blobs[gi] = blob           # keepalive until drain
        self._aio.async_pwrite(blob, self._group_file(gi))

    def _leaf_nbytes(self, i: int) -> int:
        return int(np.prod(self.opt.leaf_shapes()[i])) * \
            np.dtype(self.compute_dtype).itemsize

    def _group_nbytes(self, gi: int) -> int:
        return sum(self._leaf_nbytes(i)
                   for i in jax.tree_util.tree_leaves(self._group_idx[gi]))

    def _prefetch_group(self, gi: Optional[int]):
        """Issue the aio read for group ``gi`` into its rotating buffer slot.
        Access order is strictly sequential (fwd 0..G-1, bwd G-1..0), so
        ``slot = gi % 2`` never collides: only the current and next groups are
        live, and the current group was already COPIED out of its buffer by
        ``_device_group`` before the next prefetch lands in it."""
        if gi is None or not self._nvme or not self._nvme_groups[gi]:
            return
        if self._buf_group[gi % 2] == gi or gi in self._pending_req:
            return
        slot = gi % 2
        self._buf_group[slot] = None
        req = self._aio.async_pread(self._bufs[slot][:self._group_nbytes(gi)],
                                    self._group_file(gi))
        self._pending_req[gi] = (slot, req)

    def _host_group_tree(self, idx_tree, gi: Optional[int] = None):
        """Materialize one group's host arrays (RAM store or nvme buffer).
        NVMe leaves are COPIED out of the rotating buffer: on the CPU backend
        ``device_put`` can alias host memory, and the buffer is overwritten by
        the next prefetch."""
        if gi is not None and self._nvme and self._nvme_groups[gi]:
            slot = gi % 2
            if gi in self._pending_req:
                slot, req = self._pending_req.pop(gi)
                if self._aio.wait(req):
                    raise RuntimeError(
                        f"offload_param: nvme read failed (group {gi})")
                self._buf_group[slot] = gi
            if self._buf_group[slot] != gi:   # first touch: synchronous read
                if self._aio.wait(self._aio.async_pread(
                        self._bufs[slot][:self._group_nbytes(gi)],
                        self._group_file(gi))):
                    raise RuntimeError(
                        f"offload_param: nvme read failed (group {gi})")
                self._buf_group[slot] = gi
            buf = self._bufs[slot]
            shapes = self.opt.leaf_shapes()
            off = [0]

            def take(i):
                n = self._leaf_nbytes(i)
                view = buf[off[0]:off[0] + n].view(
                    np.dtype(self.compute_dtype)).reshape(shapes[i])
                off[0] += n
                return view.copy()
            return jax.tree.map(take, idx_tree)
        return jax.tree.map(lambda i: self._store[i], idx_tree)

    def _device_group(self, idx_tree, gi: Optional[int] = None):
        tree = self._host_group_tree(idx_tree, gi)
        self.bytes_streamed += sum(a.nbytes for a in jax.tree.leaves(tree))
        shardings = jax.tree.map(lambda i: self._leaf_sharding[i], idx_tree)
        return jax.device_put(tree, shardings)

    def _accumulate(self, idx_tree, grad_tree):
        for i, g in zip(jax.tree.leaves(idx_tree), jax.tree.leaves(grad_tree)):
            g = np.asarray(jax.device_get(g), np.float32)
            if self._accum[i] is None:
                self._accum[i] = g.copy()
            else:
                self._accum[i] += g

    # --- jitted per-group functions ------------------------------------------
    def _fwd_fn(self, n: int):
        """Group forward: returns (x_out, aux) — aux is the group's MoE
        gating loss sum (always 0.0 for dense llama, keeping one protocol)."""
        if n not in self._stack_fwd:
            if self._moe:
                stack = _MoEBlockStack(self._model_cfg, n)
                self._stack_fwd[n] = jax.jit(
                    lambda p, x, pos, seg: stack.apply({"params": p}, x, pos,
                                                       seg))
            else:
                stack = _BlockStack(self.cfg, n)
                self._stack_fwd[n] = jax.jit(
                    lambda p, x, pos, seg: (
                        stack.apply({"params": p}, x, pos, seg),
                        jnp.float32(0.0)))
        return self._stack_fwd[n]

    def _bwd_fn(self, n: int):
        """Group backward; under MoE the unit cotangent on the group's aux
        output carries the gating-loss gradient into its params."""
        if n not in self._stack_bwd:
            if self._moe:
                stack = _MoEBlockStack(self._model_cfg, n)

                def bwd(p, x, pos, seg, g):
                    _, vjp = jax.vjp(
                        lambda p_, x_: stack.apply({"params": p_}, x_, pos,
                                                   seg),
                        p, x)
                    gp, gx = vjp((g, jnp.float32(1.0)))
                    return gx, gp
            else:
                stack = _BlockStack(self.cfg, n)

                def bwd(p, x, pos, seg, g):
                    _, vjp = jax.vjp(
                        lambda p_, x_: stack.apply({"params": p_}, x_, pos,
                                                   seg),
                        p, x)
                    gp, gx = vjp(g)
                    return gx, gp
            self._stack_bwd[n] = jax.jit(bwd)
        return self._stack_bwd[n]

    def _embed_fns(self):
        if self._embed_fwd_fn is None:
            cfg = self.cfg

            def embed_fwd(emb, ids):
                x = jnp.take(emb["embed"]["embedding"].astype(cfg.dtype),
                             ids, axis=0)
                if cfg.scale_embeddings:
                    x = x * jnp.sqrt(jnp.asarray(
                        cfg.hidden_size, jnp.float32)).astype(x.dtype)
                return x

            def embed_bwd(emb, ids, g):
                _, vjp = jax.vjp(lambda e: embed_fwd(e, ids), emb)
                return vjp(g)[0]
            self._embed_fwd_fn = jax.jit(embed_fwd)
            self._embed_bwd_fn = jax.jit(embed_bwd)
        return self._embed_fwd_fn, self._embed_bwd_fn

    def _tail_grad_fn(self):
        """Tied: grads flow to (tail, embedding, x). Untied: the embedding is
        not an input at all (a [V,H] zero cotangent would cost real HBM)."""
        if self._tail_fn is None:
            tail_mod = _TailLoss(self.cfg,
                                 head_dtype=jnp.float32 if self._moe else None)
            tied = self.cfg.tie_embeddings

            def tail_grad(tail_p, embedding, x, labels, mask):
                if tied:
                    loss, vjp = jax.vjp(
                        lambda tp, emb, x_: tail_mod.apply(
                            {"params": tp}, x_, emb, labels, mask),
                        tail_p, embedding, x)
                    gt, gemb, gx = vjp(jnp.float32(1.0))
                else:
                    loss, vjp = jax.vjp(
                        lambda tp, x_: tail_mod.apply(
                            {"params": tp}, x_, None, labels, mask),
                        tail_p, x)
                    gt, gx = vjp(jnp.float32(1.0))
                    gemb = None
                return loss, gx, gt, gemb
            self._tail_fn = jax.jit(tail_grad)
        return self._tail_fn

    # --- the streamed step ----------------------------------------------------
    def _micro_grads(self, micro: Dict[str, np.ndarray]):
        cfg = self.cfg
        ids = jax.device_put(np.asarray(micro["input_ids"]),
                             self.batch_sharding)
        positions = micro.get("positions")
        positions = jnp.asarray(positions) if positions is not None else \
            jnp.broadcast_to(jnp.arange(ids.shape[1]), ids.shape)
        seg = micro.get("segment_ids")
        seg = jnp.asarray(seg) if seg is not None else None
        if seg is not None and self._moe:
            raise NotImplementedError(
                "packed-sequence segment_ids with MoE param offload is "
                "unsupported (MixtralBlock takes no segment mask)")

        # labels over all S (mask kills the shifted-out position) — equal to
        # the dense shifted loss, static shapes (LlamaForCausalLM._chunked_loss)
        labels = micro.get("labels")
        if labels is None:
            host_ids = np.asarray(micro["input_ids"])
            labels = np.pad(host_ids[:, 1:], ((0, 0), (0, 1)))
            mask = micro.get("loss_mask")
            mask = np.asarray(mask)[:, 1:] if mask is not None else \
                np.ones_like(host_ids[:, 1:])
            mask = np.pad(mask, ((0, 0), (0, 1)))
        else:
            labels = np.asarray(labels)
            mask = np.asarray(micro.get("loss_mask", np.ones_like(labels)))
        labels = jax.device_put(labels, self.batch_sharding)
        mask = jax.device_put(mask, self.batch_sharding)

        embed_fwd, embed_bwd = self._embed_fns()
        G = len(self._layer_groups)

        # ---- forward stream (prefetch g+1 while g computes) ----
        embed_dev = self._device_group(self._embed_idx)
        x = embed_fwd(embed_dev, ids)
        acts = []
        aux_total = jnp.float32(0.0)
        self._prefetch_group(0)
        nxt = self._device_group(self._group_idx[0], 0) if G else None
        for gi in range(G):
            cur = nxt
            self._prefetch_group(gi + 1 if gi + 1 < G else None)
            if gi + 1 < G:
                nxt = self._device_group(self._group_idx[gi + 1], gi + 1)
            acts.append(x)
            x, aux_g = self._fwd_fn(len(self._layer_groups[gi]))(
                cur, x, positions, seg)
            aux_total = aux_total + aux_g

        # ---- loss + head/embed-tie grads ----
        tail_dev = self._device_group(self._tail_idx)
        loss, gx, g_tail, g_emb_tie = self._tail_grad_fn()(
            tail_dev, embed_dev["embed"]["embedding"], x, labels, mask)
        # the MoE gating losses join the reported loss; their param grads
        # flow through each group's aux cotangent in the backward stream
        loss = loss + aux_total
        self._accumulate(self._tail_idx, g_tail)
        if cfg.tie_embeddings:
            self._accumulate(self._embed_idx,
                             {"embed": {"embedding": g_emb_tie}})
        del tail_dev, x

        # ---- backward stream (prefetch g-1 while g computes; grads D2H
        # overlaps the NEXT group's compute via deferred accumulation) ----
        self._prefetch_group(G - 1 if G else None)
        nxt = self._device_group(self._group_idx[G - 1], G - 1) if G else None
        pending = None                       # (idx_tree, device grads)
        for gi in range(G - 1, -1, -1):
            cur = nxt
            self._prefetch_group(gi - 1 if gi - 1 >= 0 else None)
            if gi - 1 >= 0:
                nxt = self._device_group(self._group_idx[gi - 1], gi - 1)
            gx, gp = self._bwd_fn(len(self._layer_groups[gi]))(
                cur, acts[gi], positions, seg, gx)
            for leaf in jax.tree.leaves(gp):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            if pending is not None:          # gi's bwd is dispatched; at most
                self._accumulate(*pending)   # 2 groups' grads live in HBM
            pending = (self._group_idx[gi], gp)
            del cur
        g_embed = embed_bwd(embed_dev, ids, gx)
        if pending is not None:
            self._accumulate(*pending)
        self._accumulate(self._embed_idx, g_embed)
        return loss

    def train_batch(self, stacked_batch, step: int) -> Tuple[float, float]:
        """One full batch: gas streamed microbatches + host optimizer update.
        Returns (loss, grad_norm) as host floats. Phase wall times land in
        ``self.phase_seconds`` (stream+compute vs host optimizer vs store
        refresh/write-back) for the bench ladder's swap-bandwidth rows."""
        import time as _time
        gas = self.config.gradient_accumulation_steps
        self._accum = [None] * len(self._accum)
        self.bytes_streamed = 0
        t0 = _time.perf_counter()
        losses = []
        for g in range(gas):
            micro = {k: np.asarray(v)[g] for k, v in stacked_batch.items()}
            losses.append(self._micro_grads(micro))
        loss = float(np.mean([jax.device_get(l) for l in losses]))
        t_stream = _time.perf_counter()

        grads = [a / gas if a is not None else
                 np.zeros(self.opt.leaf_shapes()[i], np.float32)
                 for i, a in enumerate(self._accum)]
        sq = sum(float(np.vdot(g, g)) for g in grads)
        norm = float(np.sqrt(sq))
        clip = self.config.gradient_clipping
        if clip and clip > 0 and norm > clip:
            scale = clip / norm
            for g in grads:
                g *= scale
        lr = float(jax.device_get(self.lr_schedule(jnp.int32(step))))
        self.opt.step(grads, lr=lr)
        t_opt = _time.perf_counter()
        self.sync_store()
        t_end = _time.perf_counter()
        self.phase_seconds = {
            "stream_fwd_bwd": round(t_stream - t0, 4),
            "host_optimizer": round(t_opt - t_stream, 4),
            "store_refresh": round(t_end - t_opt, 4),
        }
        return loss, norm

    def sync_store(self):
        """Compute-dtype store <- masters, then NVMe write-back (called after
        every optimizer update and after a checkpoint restore)."""
        self._refresh_store()
        if self._nvme:
            self._writeback_nvme()

    def _writeback_nvme(self):
        for gi in range(len(self._layer_groups)):
            if self._nvme_groups[gi]:
                self._write_group_file(gi)
        if self._aio.drain():
            raise RuntimeError("offload_param: nvme write-back failed")
        self._group_blobs = {}
        self._buf_group = [None, None]       # buffers now hold stale weights
        self._pending_req = {}
        for gi in range(len(self._layer_groups)):
            if self._nvme_groups[gi]:
                for i in jax.tree_util.tree_leaves(self._group_idx[gi]):
                    self._store[i] = None

    # --- checkpoint interop ----------------------------------------------------
    @property
    def treedef(self):
        return self._treedef

    def masters_tree(self):
        return jax.tree_util.tree_unflatten(self._treedef, self.opt.masters())

    def load_masters(self, params_tree, reset_moments: bool = False):
        self.opt.set_masters(jax.tree_util.tree_flatten(params_tree)[0],
                             reset_moments=reset_moments)
        self.sync_store()
