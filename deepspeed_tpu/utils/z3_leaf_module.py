"""ZeRO-3 leaf modules — exclude a subtree from parameter sharding.

Reference analog: ``deepspeed/utils/z3_leaf_module.py``
(``set_z3_leaf_modules``): marks module classes whose parameters ZeRO-3 should
fetch as one unit instead of hooking every child (fixes thrashing on
fine-grained modules like MoE expert stacks).

TPU redesign: "fetch granularity" doesn't exist — XLA schedules gathers — but
the useful half of the semantic survives: *don't shard below this subtree*.
``set_z3_leaf_modules`` registers parameter-path prefixes; the ZeRO partitioner
(``runtime/zero/partition.py:build_param_shardings``) keeps every leaf under a
registered prefix replicated on the fsdp axis (tensor-parallel rules still
apply), so tiny per-expert weights aren't sliced into sub-tile shards.
"""

from typing import Iterable, List

_LEAF_PREFIXES: List[str] = []


def set_z3_leaf_modules(prefixes: Iterable[str]) -> List[str]:
    """Register path prefixes/substrings (e.g. ``"experts"``) to keep unsharded
    on the fsdp axis. Returns the active registry."""
    for p in prefixes:
        if p not in _LEAF_PREFIXES:
            _LEAF_PREFIXES.append(str(p))
    return list(_LEAF_PREFIXES)


def unset_z3_leaf_modules(prefixes: Iterable[str]) -> List[str]:
    for p in prefixes:
        if p in _LEAF_PREFIXES:
            _LEAF_PREFIXES.remove(p)
    return list(_LEAF_PREFIXES)


def z3_leaf_parameters() -> List[str]:
    return list(_LEAF_PREFIXES)


def is_z3_leaf_path(path_str: str) -> bool:
    return any(p in path_str for p in _LEAF_PREFIXES)
