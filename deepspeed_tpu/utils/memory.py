"""Device/host memory reporting.

Reference analog: ``deepspeed/runtime/utils.py see_memory_usage`` (allocator
stats printed at engine milestones). TPU shape: per-device HBM stats from
``Device.memory_stats()`` (bytes_in_use / peak / limit) + host RSS.

Milestone lines now land on the dstrace timeline too: ``see_memory_usage``
emits a ``mem/see_memory_usage`` instant (which the tracer's monitor sink
fans out as an ``Events/`` gauge when a ``step`` is given), so "before
forward" / "after optimizer" memory marks line up with the dispatch/drain
spans and the dsmem HBM counter tracks instead of living only in a log
file. The log line is kept for now but is the deprecated path — consumers
should read the timeline/monitor, not scrape logs.
"""

import os
from typing import Dict, Optional

from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger


def get_memory_stats() -> Dict[str, Dict[str, float]]:
    import jax
    out = {}
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        out[str(d)] = {
            "bytes_in_use_gb": stats.get("bytes_in_use", 0) / 1e9,
            "peak_bytes_in_use_gb": stats.get("peak_bytes_in_use", 0) / 1e9,
            "bytes_limit_gb": stats.get("bytes_limit", 0) / 1e9,
        }
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        out["host"] = {"rss_gb": rss_pages * os.sysconf("SC_PAGE_SIZE") / 1e9}
    except Exception:
        pass
    return out


def see_memory_usage(message: str, force: bool = False,
                     ranks=(0,), step: Optional[int] = None
                     ) -> Optional[Dict]:
    """Record device+host memory at a milestone (reference signature:
    ``see_memory_usage(msg, force)``). Returns the stats dict for
    programmatic use.

    The ``force=False`` default is a TRUE no-op: no jax import, no device
    enumeration — callers sprinkle this at milestones unconditionally and
    the disabled path must cost nothing (the old version imported jax
    before the early return, dragging the full framework into processes
    that never wanted it)."""
    if not force:
        return None
    import jax
    if jax.process_index() not in ranks:
        return None
    stats = get_memory_stats()
    # the timeline is the primary sink: peak device bytes + host RSS ride
    # a mem/ instant (with `step` it also fans out through the tracer's
    # monitor sink as an Events/ gauge)
    tracer = get_tracer()
    if tracer.enabled:
        peak = max((s.get("peak_bytes_in_use_gb", 0.0)
                    for d, s in stats.items() if d != "host"), default=0.0)
        tracer.instant(
            "mem/see_memory_usage", cat="mem", step=step, message=message,
            peak_gb=round(peak, 4),
            rss_gb=round(stats.get("host", {}).get("rss_gb", 0.0), 4))
    parts = []
    for dev, s in stats.items():
        if dev == "host":
            parts.append(f"host rss {s['rss_gb']:.2f}GB")
        else:
            parts.append(f"{dev}: {s['bytes_in_use_gb']:.2f}GB in use "
                         f"(peak {s['peak_bytes_in_use_gb']:.2f}GB)")
    # deprecated sink: kept for operators tailing logs, but the timeline
    # instant above is the contract going forward
    logger.info(f"MEM {message} | " + " | ".join(parts))
    return stats
