"""Device/host memory reporting.

Reference analog: ``deepspeed/runtime/utils.py see_memory_usage`` (allocator
stats printed at engine milestones). TPU shape: per-device HBM stats from
``Device.memory_stats()`` (bytes_in_use / peak / limit) + host RSS.
"""

import os
from typing import Dict, Optional

from deepspeed_tpu.utils.logging import logger


def get_memory_stats() -> Dict[str, Dict[str, float]]:
    import jax
    out = {}
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        out[str(d)] = {
            "bytes_in_use_gb": stats.get("bytes_in_use", 0) / 1e9,
            "peak_bytes_in_use_gb": stats.get("peak_bytes_in_use", 0) / 1e9,
            "bytes_limit_gb": stats.get("bytes_limit", 0) / 1e9,
        }
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        out["host"] = {"rss_gb": rss_pages * os.sysconf("SC_PAGE_SIZE") / 1e9}
    except Exception:
        pass
    return out


def see_memory_usage(message: str, force: bool = False,
                     ranks=(0,)) -> Optional[Dict]:
    """Log device+host memory (reference signature: see_memory_usage(msg,
    force)). Returns the stats dict for programmatic use."""
    import jax
    if not force:
        return None
    if jax.process_index() not in ranks:
        return None
    stats = get_memory_stats()
    parts = []
    for dev, s in stats.items():
        if dev == "host":
            parts.append(f"host rss {s['rss_gb']:.2f}GB")
        else:
            parts.append(f"{dev}: {s['bytes_in_use_gb']:.2f}GB in use "
                         f"(peak {s['peak_bytes_in_use_gb']:.2f}GB)")
    logger.info(f"MEM {message} | " + " | ".join(parts))
    return stats
