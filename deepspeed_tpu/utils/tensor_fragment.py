"""Debug access to full fp32 params / optimizer state under any sharding.

Reference analog: ``deepspeed/utils/tensor_fragment.py`` — maps each rank's
low-precision fragment to its slice of the fp32 master flat buffer so user code
can call ``safe_get_full_fp32_param`` / ``safe_get_full_optimizer_state`` /
``safe_set_full_fp32_param`` regardless of ZeRO stage (the fragment bookkeeping
is also what universal checkpointing rides on).

TPU redesign: there are no fragments to map — ``engine.state.params`` leaves
are *global* ``jax.Array``\\ s whose shards live across the mesh; fetching one
is a ``jax.device_get`` (XLA gathers), setting one is a ``device_put`` to the
leaf's NamedSharding. What remains of the reference API is path-based lookup
into the state pytree, which these helpers provide with the same spellings.
"""

from typing import Any, Optional

import jax
import numpy as np


from deepspeed_tpu.utils.tree import tree_path_str as _path_str


def _find_leaf(tree: Any, name: str):
    """(path_str, leaf) for the unique leaf whose path contains ``name``."""
    hits = [(p, leaf) for p, leaf in
            jax.tree_util.tree_flatten_with_path(tree)[0]
            if name in _path_str(p)]
    if not hits:
        raise KeyError(f"no state leaf matches {name!r}")
    if len(hits) > 1:
        paths = [_path_str(p) for p, _ in hits][:5]
        raise KeyError(f"{name!r} is ambiguous: {paths}")
    return hits[0]


def _leaf_index(tree: Any, name: str) -> int:
    """Flat-leaf index (jax.tree.leaves order) of the unique match — the order
    the host-offload tier stores its master list in (engine.py builds it from
    ``jax.tree.leaves(params)``)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    hits = [i for i, (p, _) in enumerate(flat) if name in _path_str(p)]
    if len(hits) != 1:
        raise KeyError(f"{name!r} matched {len(hits)} leaves")
    return hits[0]


def safe_get_full_fp32_param(engine, name: str) -> np.ndarray:
    """Full (gathered) fp32 master value of the parameter whose path contains
    ``name`` (reference ``tensor_fragment.py:safe_get_full_fp32_param``).
    Under optimizer host-offload the authoritative fp32 masters live on the
    host tier — ``engine.state.params`` are compute-dtype shadows — so the
    master list is consulted first."""
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        idx = _leaf_index(engine.state.params, name)
        return np.asarray(offload.masters()[idx], dtype=np.float32)
    _, leaf = _find_leaf(engine.state.params, name)
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_set_full_fp32_param(engine, name: str, value) -> None:
    """Overwrite a master parameter, re-laying it out onto the leaf's existing
    sharding (reference ``safe_set_full_fp32_param``)."""
    path, leaf = _find_leaf(engine.state.params, name)
    value = np.asarray(value, dtype=np.float32).reshape(np.shape(leaf))
    path_s = _path_str(path)
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        # write the authoritative host master in place (masters() returns the
        # live buffers; a set_masters() round-trip would memcpy every leaf),
        # then fall through to refresh the device shadow for the next forward
        idx = _leaf_index(engine.state.params, name)
        np.copyto(offload.masters()[idx], value)

    def replace(p, l):
        if _path_str(p) == path_s:
            return jax.device_put(value.astype(l.dtype), l.sharding)
        return l

    new_params = jax.tree_util.tree_map_with_path(replace, engine.state.params)
    engine.state = engine.state._replace(params=new_params)


def safe_get_full_optimizer_state(engine, name: str,
                                  state_name: str = "mu") -> np.ndarray:
    """Gathered optimizer-state leaf (``mu``/``nu`` for adam moments) matching
    a parameter path (reference ``safe_get_full_optimizer_state``)."""
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        idx = _leaf_index(engine.state.params, name)
        slot = {"mu": 0, "exp_avg": 0, "nu": 1, "exp_avg_sq": 1}.get(state_name)
        if slot is None:
            raise KeyError(f"unknown offloaded state {state_name!r}")
        # per-leaf materialization: swap in only this leaf's moments (a full
        # state_dict() would drag every NVMe leaf into host RAM)
        states = offload._materialized_states(offload.leaves[idx])
        if slot >= len(states):
            raise KeyError(f"{state_name!r}: optimizer keeps {len(states)} "
                           "state slots")
        return np.asarray(states[slot], dtype=np.float32)
    pstate = _find_optimizer_tree(engine.state.opt_state, state_name)
    _, leaf = _find_leaf(pstate, name)
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_get_full_grad(engine, name: str) -> Optional[np.ndarray]:
    """Last step's gradient if the engine retains one. The fused train step
    consumes grads inside jit (they never persist), so this returns None unless
    the engine ran a compat ``backward()`` that kept ``engine.last_grads`` —
    mirrored from the reference where grads are also None post-step."""
    grads = getattr(engine, "last_grads", None)
    if grads is None:
        return None
    _, leaf = _find_leaf(grads, name)
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def _find_optimizer_tree(opt_state: Any, state_name: str):
    """Locate the sub-tree of an optax state owning ``state_name`` (e.g. the
    ScaleByAdamState with .mu/.nu)."""
    found = []

    def visit(node):
        if hasattr(node, state_name):
            found.append(getattr(node, state_name))
            return
        if isinstance(node, (tuple, list)):
            for c in node:
                visit(c)

    visit(opt_state)
    if not found:
        raise KeyError(f"optimizer state has no {state_name!r} collection")
    return found[0]
