"""Profiler range annotation.

Reference analog: ``deepspeed/utils/nvtx.py`` (``instrument_w_nvtx`` pushes an
NVTX range via ``get_accelerator().range_push/pop`` around hot functions, e.g.
every ZeRO-3 coordinator method).

TPU redesign: ranges are ``jax.named_scope`` (names land in the HLO and show up
in XLA/TPU profiler traces under the op hierarchy) plus
``jax.profiler.TraceAnnotation`` for host-side spans (visible in perfetto
traces captured by ``jax.profiler.trace``). One decorator serves both: inside
jit the named_scope tags the emitted ops; outside it the TraceAnnotation times
the Python call.

Single source of span truth: every range ALSO lands in the dstrace tracer
(``deepspeed_tpu.telemetry``) when tracing is on, so annotated hot functions
show up in the same Chrome-trace timeline as the engine's dispatch/drain/
checkpoint spans — without a second capture mechanism. When tracing is off
the extra cost is one attribute read (the no-op fast path).
"""

import functools

import jax

from deepspeed_tpu.telemetry.tracer import get_tracer


def instrument(fn=None, *, name: str = None):
    """Decorator: wrap ``fn`` in a profiler range named after it (reference
    ``instrument_w_nvtx``). Usable bare (``@instrument``) or with a name
    (``@instrument(name="fetch")``)."""
    if fn is None:
        return functools.partial(instrument, name=name)
    label = name or getattr(fn, "__qualname__", getattr(fn, "__name__", "fn"))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with get_tracer().span(label, cat="annotate"), \
                jax.profiler.TraceAnnotation(label), jax.named_scope(label):
            return fn(*args, **kwargs)

    return wrapped


# reference-name alias so call sites read the same
instrument_w_nvtx = instrument


class _Annotation:
    """``annotate``/``range_push`` context: one jax TraceAnnotation + (when
    tracing is on) one dstrace span, entered and exited together."""
    __slots__ = ("_name", "_jax_ctx", "_span")

    def __init__(self, name: str):
        self._name = name
        self._jax_ctx = None
        self._span = None

    def __enter__(self):
        tracer = get_tracer()
        self._span = tracer.span(self._name, cat="annotate") \
            if tracer.enabled else None
        self._jax_ctx = jax.profiler.TraceAnnotation(self._name)
        self._jax_ctx.__enter__()
        if self._span is not None:
            self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._span.__exit__(exc_type, exc, tb)
            self._span = None
        ctx, self._jax_ctx = self._jax_ctx, None
        return ctx.__exit__(exc_type, exc, tb)


def annotate(name: str):
    """``with annotate("step"): ...`` — host-side profiler span (jax
    TraceAnnotation + dstrace span when tracing is enabled)."""
    return _Annotation(name)


def range_push(name: str):
    """Manual range begin (reference accelerator.range_push). Returns a context
    object; prefer ``with annotate(name):``."""
    ctx = annotate(name)
    ctx.__enter__()
    return ctx


def range_pop(ctx) -> None:
    ctx.__exit__(None, None, None)
