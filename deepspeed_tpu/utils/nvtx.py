"""Profiler range annotation.

Reference analog: ``deepspeed/utils/nvtx.py`` (``instrument_w_nvtx`` pushes an
NVTX range via ``get_accelerator().range_push/pop`` around hot functions, e.g.
every ZeRO-3 coordinator method).

TPU redesign: ranges are ``jax.named_scope`` (names land in the HLO and show up
in XLA/TPU profiler traces under the op hierarchy) plus
``jax.profiler.TraceAnnotation`` for host-side spans (visible in perfetto
traces captured by ``jax.profiler.trace``). One decorator serves both: inside
jit the named_scope tags the emitted ops; outside it the TraceAnnotation times
the Python call.
"""

import functools

import jax


def instrument(fn=None, *, name: str = None):
    """Decorator: wrap ``fn`` in a profiler range named after it (reference
    ``instrument_w_nvtx``). Usable bare (``@instrument``) or with a name
    (``@instrument(name="fetch")``)."""
    if fn is None:
        return functools.partial(instrument, name=name)
    label = name or getattr(fn, "__qualname__", getattr(fn, "__name__", "fn"))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
            return fn(*args, **kwargs)

    return wrapped


# reference-name alias so call sites read the same
instrument_w_nvtx = instrument


def range_push(name: str):
    """Manual range begin (reference accelerator.range_push). Returns a context
    object; prefer ``with annotate(name):``."""
    ctx = jax.profiler.TraceAnnotation(name)
    ctx.__enter__()
    return ctx


def range_pop(ctx) -> None:
    ctx.__exit__(None, None, None)


def annotate(name: str):
    """``with annotate("step"): ...`` — host-side profiler span."""
    return jax.profiler.TraceAnnotation(name)
