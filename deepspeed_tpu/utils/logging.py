"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (``log_dist``,
``logger``): a module-level logger plus rank-filtered helpers. On TPU the "rank" is
``jax.process_index()`` (one process per host under multi-host SPMD), not a per-device
rank — devices within a process share the log stream.
"""

import logging
import os
import sys
from typing import Iterable, Optional

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stderr)
        handler.setFormatter(logging.Formatter(LOG_FORMAT))
        logger_.addHandler(handler)
    return logger_


logger = create_logger(
    level=getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO))


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed process indices (None or [-1] => all).

    Mirrors the reference's ``log_dist`` semantics (deepspeed/utils/logging.py) with
    ``jax.process_index()`` standing in for the torch.distributed rank.
    """
    my_rank = _process_index()
    if ranks is None or len(list(ranks)) == 0:
        should = my_rank == 0
    else:
        ranks = list(ranks)
        should = (-1 in ranks) or (my_rank in ranks)
    if should:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
