"""Abstract ("meta"-device) model construction.

Reference analog: ``deepspeed/utils/init_on_device.py`` (``OnDevice`` context:
patches ``Tensor.__new__``/module ``__init__`` so a model builds with
meta-device tensors — no host/device memory — until real weights arrive), used
by ZeRO-3's ``zero.Init`` to construct >RAM models.

TPU redesign: flax modules are already lazy — parameters exist only when
``init`` runs — so the meta-device trick reduces to two first-class functions:

- ``abstract_init``: ``jax.eval_shape`` over ``model.init`` — the full param
  pytree as ShapeDtypeStructs, zero bytes allocated. This is what the engine
  uses to plan shardings before any weight exists.
- ``sharded_init``: jit ``model.init`` with ``out_shardings`` from the ZeRO
  partitioner so every parameter materializes *directly into its shard* —
  no rank ever holds a full replica (the actual ``zero.Init`` semantic:
  reference ``runtime/zero/partition_parameters.py:816``).
"""

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.runtime.zero.partition import build_param_shardings


def abstract_init(model, rng, *args, method: Optional[Callable] = None,
                  **kwargs) -> Any:
    """Shape/dtype pytree of ``model.init(rng, *args)`` with no allocation."""
    return jax.eval_shape(
        lambda r: model.init(r, *args, method=method, **kwargs)
        if method else model.init(r, *args, **kwargs), rng)


def sharded_init(model, rng, *args, mesh, stage: int = 3,
                 tensor_rules: Optional[Callable] = None, **kwargs) -> Any:
    """Initialize directly into ZeRO-``stage`` shards over ``mesh``.

    Returns ``(variables, shardings)``: every leaf of ``variables`` is born
    sharded per the partitioner — construction memory per device is
    ``params / fsdp_size``, the zero.Init contract."""
    shapes = abstract_init(model, rng, *args, **kwargs)
    params = shapes.get("params", shapes) if isinstance(shapes, dict) else shapes
    shardings = build_param_shardings(params, mesh, stage=stage,
                                      tensor_rules=tensor_rules)
    out_sh = dict(shapes, params=shardings) if isinstance(shapes, dict) and \
        "params" in shapes else shardings
    # non-param collections (batch_stats, cache...) default to replicated
    out_sh = jax.tree.map(
        lambda s: s if hasattr(s, "spec") else None, out_sh,
        is_leaf=lambda x: hasattr(x, "spec") or x is None)
    with mesh:
        variables = jax.jit(
            lambda r: model.init(r, *args, **kwargs),
            out_shardings=out_sh)(rng)
    return variables, shardings


class OnDevice:
    """Context-manager shim with the reference's spelling. flax needs no
    patching, so this only records the requested dtype/device and offers
    ``abstract_init``/``sharded_init`` bound to them."""

    def __init__(self, dtype=None, device: str = "meta", enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    abstract_init = staticmethod(abstract_init)
    sharded_init = staticmethod(sharded_init)
