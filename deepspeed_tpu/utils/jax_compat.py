"""Version-drift shims for the jax API surface this tree targets.

The codebase is written against the promoted locations (``jax.shard_map``,
``jax.distributed.is_initialized``); older jax releases only carry the
experimental/private ones. Importing this module aliases the old locations
onto the new names so one tree runs on both. Imported once from the package
root, before any call site.
"""

import jax


def ensure_compat():
    if not hasattr(jax, "shard_map"):
        import functools
        import inspect

        from jax.experimental.shard_map import shard_map
        accepts_vma = "check_vma" in inspect.signature(shard_map).parameters

        @functools.wraps(shard_map)
        def _shard_map(*args, **kwargs):
            if not accepts_vma and "check_vma" in kwargs:
                # the kwarg was renamed check_rep -> check_vma upstream
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return shard_map(*args, **kwargs)

        jax.shard_map = _shard_map
    if not hasattr(jax.distributed, "is_initialized"):
        def _is_initialized():
            try:
                from jax._src import distributed
                return distributed.global_state.client is not None
            except Exception:
                return False
        jax.distributed.is_initialized = _is_initialized


ensure_compat()
