"""Version-drift shims for the jax API surface this tree targets.

The codebase is written against the promoted locations (``jax.shard_map``,
``jax.distributed.is_initialized``); older jax releases only carry the
experimental/private ones. Importing this module aliases the old locations
onto the new names so one tree runs on both. Imported once from the package
root, before any call site.
"""

import jax


def ensure_compat():
    if not hasattr(jax, "shard_map"):
        import functools
        import inspect

        from jax.experimental.shard_map import shard_map
        params = inspect.signature(shard_map).parameters
        accepts_vma = "check_vma" in params
        accepts_axis_names = "axis_names" in params
        accepts_auto = "auto" in params

        @functools.wraps(shard_map)
        def _shard_map(*args, **kwargs):
            if not accepts_vma and "check_vma" in kwargs:
                # the kwarg was renamed check_rep -> check_vma upstream
                kwargs["check_rep"] = kwargs.pop("check_vma")
            if not accepts_axis_names and "axis_names" in kwargs:
                # newer jax: axis_names picks the manual subset of mesh
                # axes. Old shard_map is all-manual; axes left out of the
                # in/out specs are simply replicated per shard, which is
                # equivalent for the collectives the body actually names
                # (translating to the old `auto=` complement instead
                # aborts XLA compilation on jaxlib 0.4.37 CPU)
                kwargs.pop("axis_names")
            return shard_map(*args, **kwargs)

        jax.shard_map = _shard_map
    if not hasattr(jax.lax, "axis_size"):
        # promoted in later releases; older jax exposes the bound size
        # through the axis env (core.axis_frame(name) IS the size there)
        def _axis_size(axis_name):
            import jax.core as core
            names = axis_name if isinstance(axis_name, (tuple, list)) \
                else (axis_name,)
            size = 1
            for n in names:
                size *= int(core.axis_frame(n))
            return size
        jax.lax.axis_size = _axis_size
    if not hasattr(jax.distributed, "is_initialized"):
        def _is_initialized():
            try:
                from jax._src import distributed
                return distributed.global_state.client is not None
            except Exception:
                return False
        jax.distributed.is_initialized = _is_initialized


ensure_compat()
