"""Pytree path utilities shared across the partitioner, policies, and debug APIs.

(Reference keeps the analogous parameter-naming helpers in
``deepspeed/utils/tensor_fragment.py`` / ``runtime/utils.py``.)
"""


def tree_path_str(path) -> str:
    """Render a jax tree path (DictKey/SequenceKey/... entries) as
    ``"model/layer_0/attn/wq/kernel"`` — the canonical spelling every
    path-matching rule in the framework keys on."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
