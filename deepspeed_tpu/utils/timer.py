"""Wall-clock + throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` at :44, ``ThroughputTimer`` at :199). CUDA events do not
exist here; synchronization is ``jax.block_until_ready`` on a token array, which forces
completion of all previously enqueued XLA work on the device.
"""

import collections
import threading
import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"
# async step pipeline: host time spent *dispatching* a step (enqueue only, no
# completion wait) — the gap between launches that latency hiding minimizes.
# True per-step time is reconciled into TRAIN_BATCH_TIMER at each metric drain.
TRAIN_BATCH_DISPATCH_TIMER = "train_batch_dispatch"


def _device_sync():
    try:
        import jax
        # Touching a tiny computation and blocking flushes the async dispatch queue.
        jax.block_until_ready(jax.numpy.zeros(()))
    except Exception:
        pass


class Timer:
    """A single named timer with start/stop/elapsed, mean and total."""

    def __init__(self, name: str, synchronize: bool = True):
        self.name = name
        self.synchronize = synchronize
        self._started = False
        self._ever_started = False
        self._start_time = 0.0
        self._elapsed = 0.0
        self._records: List[float] = []

    def start(self):
        if self._started:
            return
        if self.synchronize:
            _device_sync()
        self._start_time = time.time()
        self._started = True
        self._ever_started = True

    def stop(self, record: bool = True):
        if not self._started:
            return
        if self.synchronize:
            _device_sync()
        delta = time.time() - self._start_time
        self._elapsed += delta
        if record:
            self._records.append(delta)
        self._started = False

    def reset(self):
        self._started = False
        self._elapsed = 0.0
        self._records = []

    def record_external(self, seconds: float, count: int = 1):
        """Fold externally measured wall time into this timer as ``count``
        equal records. The async step pipeline's reconciliation hook: per-step
        start/stop in ``synchronize=False`` mode only sees dispatch time, so
        the engine measures the true drain-to-drain window (whose end is
        anchored by the drain's device_get) and books it here."""
        self._ever_started = True
        seconds = max(float(seconds), 0.0)
        count = max(int(count), 1)
        self._elapsed += seconds
        self._records.extend([seconds / count] * count)

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed seconds since last reset (stops/restarts a running timer)."""
        if not self._ever_started:
            logger.warning(f"timer '{self.name}': elapsed() before any "
                           "start(); returning 0.0")
            return 0.0
        was_started = self._started
        if was_started:
            self.stop(record=False)
        value = self._elapsed
        if reset:
            self._elapsed = 0.0
            self._records = []
        if was_started:
            self.start()
        return value

    def mean(self) -> float:
        if not self._ever_started:
            logger.warning(f"timer '{self.name}': mean() before any start(); "
                           "returning 0.0")
            return 0.0
        return sum(self._records) / len(self._records) if self._records else 0.0


class SynchronizedWallClockTimer:
    """Registry of named timers (reference: utils/timer.py:44).

    ``synchronize=False`` makes every timer measure dispatch time only (no
    device round trip per start/stop) — the engine uses this unless
    ``wall_clock_breakdown`` is on, mirroring the reference's gating of
    EngineTimers; on tunneled TPU platforms a device sync costs a full RTT.
    """

    def __init__(self, synchronize: bool = True):
        self.timers: Dict[str, Timer] = {}
        self.synchronize = synchronize

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name, synchronize=self.synchronize)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False) -> None:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        if parts:
            logger.info("time (ms) | " + " | ".join(parts))

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        out = {}
        for name in names:
            if name in self.timers:
                out[name] = self.timers[name].mean() * 1000.0 / normalizer
        return out


class ThroughputTimer:
    """samples/sec + TFLOPS reporting (reference: utils/timer.py:199).

    ``flops_per_sample`` may be supplied by the engine (e.g. from the flops profiler /
    XLA cost analysis) to report model TFLOPS at ``steps_per_print`` boundaries.

    Throughput is measured **edge to edge**: the wall clock is read (after a
    device sync) at report-window boundaries only, and the window's samples are
    divided by the full boundary-to-boundary interval. Per-step timing would
    undercount whenever the caller itself syncs between steps (e.g.
    ``float(loss)`` for logging) — the device work would then drain in the
    untimed gap between ``stop()`` and the next ``start()`` and the report
    would only see ~ms dispatch times. Edge-to-edge includes those gaps by
    construction, at one device round trip per window.

    ``synchronize=False`` (async step pipeline): start/stop never touch the
    device and NEVER close a window on their own — only ``mark_edge()``,
    called by the engine right after a metric-ring drain (whose batched
    ``device_get`` already proves the drained steps' device work finished),
    closes windows. Throughput stays honest without any extra sync.
    """

    def __init__(self, batch_size: int, steps_per_output: int = 100,
                 monitor_memory: bool = False, logging_fn=None,
                 synchronize: bool = True):
        self.batch_size = max(1, batch_size)
        self.steps_per_output = steps_per_output
        self.synchronize = synchronize
        self.logging = logging_fn or logger.info
        self.started = False
        self.global_step_count = 0
        self.steps_since_edge = 0
        self.total_elapsed_time = 0.0   # sum over completed report windows
        self._steps_in_total = 0        # steps covered by total_elapsed_time
        self._edge_time: Optional[float] = None
        self._last_report_step = 0
        self.flops_per_sample: Optional[float] = None

    def start(self):
        self.started = True
        if self._edge_time is None:
            if self.synchronize:
                _device_sync()
            self._edge_time = time.time()

    def stop(self, global_step: bool = True, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        if not global_step:
            return
        self.global_step_count += 1
        self.steps_since_edge += 1
        if self.synchronize and self.steps_per_output and \
                self.global_step_count % self.steps_per_output == 0:
            _device_sync()   # drain device work belonging to this window
            self._close_window(report_speed)

    def mark_edge(self, report_speed: bool = True):
        """Close the current window at a caller-guaranteed completion point
        (the async engine calls this right after its drain's device_get, so
        no device sync happens here). Reports at ``steps_per_output`` cadence
        like the synchronous path."""
        if self.steps_since_edge == 0:
            if self._edge_time is None:
                self._edge_time = time.time()
            return
        report = (report_speed and bool(self.steps_per_output)
                  and self.global_step_count - self._last_report_step
                  >= self.steps_per_output)
        self._close_window(report)

    def _close_window(self, report_speed: bool):
        now = time.time()
        window = max(now - self._edge_time, 1e-9)
        self.total_elapsed_time += window
        self._steps_in_total += self.steps_since_edge
        if report_speed:
            sps = self.batch_size * self.steps_since_edge / window
            msg = (f"epoch step {self.global_step_count}: "
                   f"{sps:.1f} samples/s, batch time "
                   f"{window / self.steps_since_edge * 1000:.1f} ms")
            if self.flops_per_sample:
                msg += f", {sps * self.flops_per_sample / 1e12:.2f} TFLOPS"
            self.logging(msg)
            self._last_report_step = self.global_step_count
        self._edge_time = now
        self.steps_since_edge = 0

    def avg_samples_per_sec(self) -> float:
        """Cumulative samples/sec over completed report windows (falls back to
        the partial current window, without a sync, if none completed yet)."""
        if self._steps_in_total > 0 and self.total_elapsed_time > 0:
            return self.batch_size * self._steps_in_total / self.total_elapsed_time
        if self.steps_since_edge > 0 and self._edge_time is not None:
            partial = max(time.time() - self._edge_time, 1e-9)
            return self.batch_size * self.steps_since_edge / partial
        return 0.0


class RateTracker:
    """Rolling events/sec over a sliding wall-clock window (serving
    throughput gauges: tokens/sec, requests/sec). Thread-safe; no device
    sync — serving rates time host-observed events, not XLA completion."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = window_s
        self._events = collections.deque()   # (monotonic_ts, count)
        self._start = time.monotonic()
        self._lock = threading.Lock()

    def add(self, n: float = 1.0, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, n))
            self._prune(now)

    def _prune(self, now: float):
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def rate(self, now: Optional[float] = None) -> float:
        """Events/sec averaged over the full window (0.0 when empty). The
        divisor is the window span — not the oldest-event age, which would
        spike absurdly for a single event right after an idle period — and
        shrinks to the tracker's lifetime while younger than the window."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._prune(now)
            if not self._events:
                return 0.0
            span = max(min(self.window_s, now - self._start), 1e-9)
            return sum(n for _, n in self._events) / span
