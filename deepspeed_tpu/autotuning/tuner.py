"""Tuner strategies + cost model.

Reference analog: ``deepspeed/autotuning/tuner/{base_tuner.py,index_based_tuner.py,
model_based_tuner.py,cost_model.py}`` — grid/random tuners plus an XGBoost cost model
that predicts experiment metrics from config features to order the search.

TPU redesign: same strategy split, but the cost model is a closed-form least-squares
fit (polynomial in log micro-batch + one-hot ZeRO stage) — no heavyweight ML dep, and
the search space here is small because sharding layouts replace most of the
reference's offload/bucket knobs.
"""

import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Experiment:
    """One candidate config + its measured result."""

    def __init__(self, name: str, overrides: Dict[str, Any]):
        self.name = name
        self.overrides = overrides  # config-dict fragment merged over the base
        self.status = "pending"    # pending | running | done | failed | oom
        self.metrics: Dict[str, float] = {}
        self.error: Optional[str] = None
        # dsmem forensics for oom-classified failures: live device stats +
        # the analytic ledger of the candidate config (scheduler.py fills
        # it; autotuning_results.json carries it per experiment)
        self.memory: Optional[Dict[str, Any]] = None

    def metric(self, key: str) -> Optional[float]:
        return self.metrics.get(key)

    def __repr__(self):
        return (f"Experiment({self.name}, status={self.status}, "
                f"metrics={self.metrics})")


def _features(exp: Experiment) -> List[float]:
    mbs = float(exp.overrides.get("train_micro_batch_size_per_gpu", 1))
    stage = int(exp.overrides.get("zero_optimization", {}).get("stage", 0))
    remat = 1.0 if exp.overrides.get("activation_checkpointing") else 0.0
    onehot = [1.0 if stage == s else 0.0 for s in range(4)]
    return ([1.0, np.log2(max(mbs, 1.0)), np.log2(max(mbs, 1.0)) ** 2, remat]
            + onehot)


class CostModel:
    """Least-squares regression metric ~ features (reference: cost_model.py
    XGBoostCostModel.fit/predict)."""

    def __init__(self):
        self._w: Optional[np.ndarray] = None

    def fit(self, exps: Sequence[Experiment], metric: str):
        pts = [(e, e.metrics[metric]) for e in exps
               if e.status == "done" and metric in e.metrics]
        if len(pts) < 2:
            self._w = None
            return
        X = np.array([_features(e) for e, _ in pts])
        y = np.array([v for _, v in pts])
        self._w, *_ = np.linalg.lstsq(X, y, rcond=None)

    def predict(self, exp: Experiment) -> float:
        if self._w is None:
            return 0.0
        return float(np.array(_features(exp)) @ self._w)


class BaseTuner:
    """Pulls experiments, runs them via ``runner``, tracks the best (reference:
    base_tuner.py ``BaseTuner.tune`` with early stopping)."""

    def __init__(self, exps: List[Experiment], runner: Callable[[Experiment], None],
                 metric: str = "throughput", higher_is_better: bool = True):
        self.all_exps = list(exps)
        self.runner = runner
        self.metric = metric
        self.higher_is_better = higher_is_better
        self.best_exp: Optional[Experiment] = None
        self.records: List[Experiment] = []

    def next_batch(self, n: int) -> List[Experiment]:
        batch, self.all_exps = self.all_exps[:n], self.all_exps[n:]
        return batch

    def has_next(self) -> bool:
        return bool(self.all_exps)

    def _better(self, a: float, b: float) -> bool:
        return a > b if self.higher_is_better else a < b

    def update_best(self, exp: Experiment):
        v = exp.metric(self.metric)
        if v is None:
            return
        if self.best_exp is None or self._better(v, self.best_exp.metrics[self.metric]):
            self.best_exp = exp

    def tune(self, sample_size: int = 1, n_trials: int = 50,
             early_stopping: int = 0) -> Optional[Experiment]:
        trials = 0
        since_best = 0
        while self.has_next() and trials < n_trials:
            for exp in self.next_batch(sample_size):
                self.runner(exp)
                self.records.append(exp)
                prev_best = self.best_exp
                self.update_best(exp)
                trials += 1
                since_best = 0 if self.best_exp is not prev_best else since_best + 1
            if early_stopping and since_best >= early_stopping:
                break
        return self.best_exp


class GridSearchTuner(BaseTuner):
    """Exhaustive, in given order (reference: index_based_tuner.py)."""


class RandomTuner(BaseTuner):
    """Uniform random order (reference: index_based_tuner.py RandomTuner)."""

    def __init__(self, *args, seed: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        random.Random(seed).shuffle(self.all_exps)


class ModelBasedTuner(BaseTuner):
    """Seed with a few measured points, then repeatedly re-fit the cost model and
    run the unexplored candidate with the best predicted metric (reference:
    model_based_tuner.py ``find_estimated_top_configs``)."""

    def __init__(self, *args, seed_trials: int = 3, **kwargs):
        super().__init__(*args, **kwargs)
        self.seed_trials = seed_trials
        self.cost_model = CostModel()

    def next_batch(self, n: int) -> List[Experiment]:
        if len(self.records) < self.seed_trials or not self.all_exps:
            return super().next_batch(n)
        self.cost_model.fit(self.records, self.metric)
        scored = sorted(self.all_exps, key=self.cost_model.predict,
                        reverse=self.higher_is_better)
        batch = scored[:n]
        for b in batch:
            self.all_exps.remove(b)
        return batch
