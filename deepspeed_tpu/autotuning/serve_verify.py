"""The verify half of the serving proposal loop (``dstpu plan --serve``).

The training planner's closed loop (PR 7: plan -> Autotuner executes ->
exact span-count verdict) applied to serving: each serve-plan proposal
carries ONE executable serving-config override and an exact counter
prediction ``{counter, op, value}`` over bench_serve's deterministic proof
set (sheds, demotion bytes, prefix evictions, brownout entries, ...).
``verify_serve_plan`` re-executes the SAME seeded bench_serve preset the
plan was attributed from — provenance records preset, seed, the full
scenario and the server-builder args — once per proposal with its override
applied, and judges the prediction EXACTLY against the re-run's counters
(no wall-clock, no tolerance: the comparison either holds or it doesn't).

Verdicts — ``verified`` / ``refuted`` / ``unverified`` (the re-run died or
the counter is missing) — persist under ``plan.serve_verifications`` in
``autotuning_results.json``, next to the training loop's
``plan.verifications``, and are written back into the plan artifact when
one is given so ``env_report`` can tally them.
"""

import dataclasses
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

RESULTS_NAME = "autotuning_results.json"

_OPS: Dict[str, Callable[[float, float], bool]] = {
    "<=": lambda observed, value: observed <= value,
    ">=": lambda observed, value: observed >= value,
    "<": lambda observed, value: observed < value,
    ">": lambda observed, value: observed > value,
    "==": lambda observed, value: observed == value,
}


def _load_plan(plan: Any) -> Tuple[dict, Optional[str]]:
    """Accept a serve-plan report dict or an artifact path (returns the
    path too, so verdicts can be written back into the artifact)."""
    if isinstance(plan, dict):
        return plan, None
    if isinstance(plan, str):
        with open(plan) as f:
            return json.load(f), plan
    raise ValueError(f"plan must be a serve-plan report dict or artifact "
                     f"path, got {type(plan).__name__}")


def _lookup_counter(report: dict, name: str) -> Optional[float]:
    """Find a predicted counter in a bench_serve report: the deterministic
    proof set first, then the prefix section, then the raw metrics."""
    for section in ("counters", "prefix", "metrics"):
        vals = report.get(section) or {}
        if name in vals:
            try:
                return float(vals[name])
            except (TypeError, ValueError):
                return None
    return None


def verify_serve_plan(plan: Any, results_dir: Optional[str] = None,
                      requests: Optional[int] = None,
                      build_server: Optional[Callable] = None,
                      max_proposals: Optional[int] = None
                      ) -> List[Dict[str, Any]]:
    """Re-execute the plan's seeded preset once per executable proposal
    with the proposed serving override applied; judge each counter
    prediction exactly. Returns the verdict list (and persists it — see
    module docstring). ``requests`` overrides the preset's request count
    (scaled drills make the same predictions: they were computed from the
    baseline run's own counters). ``build_server`` replaces the tiny-llama
    builder (tests inject engine doubles). ``max_proposals`` bounds the
    re-run count (proposals are verified in plan order: dominant signal
    first)."""
    from deepspeed_tpu.serving import bench_serve
    from deepspeed_tpu.telemetry.tracer import get_tracer

    plan, artifact_path = _load_plan(plan)
    prov = plan.get("provenance") or {}
    proposals = plan.get("proposals") or []
    if max_proposals is not None:
        proposals = proposals[:max_proposals]
    verifications: List[Dict[str, Any]] = []
    scenario = None
    sc_dict = prov.get("scenario")
    if sc_dict:
        known = {f.name for f in dataclasses.fields(bench_serve.ServeScenario)}
        kwargs = {k: (tuple(v) if isinstance(v, list) else v)
                  for k, v in sc_dict.items() if k in known}
        scenario = bench_serve.ServeScenario(**kwargs)
    elif prov.get("preset") in bench_serve.SCENARIOS:
        scenario = bench_serve.SCENARIOS[prov["preset"]]
        if prov.get("seed") is not None:
            scenario = dataclasses.replace(scenario, seed=prov["seed"])
    if scenario is not None and requests is not None:
        scenario = dataclasses.replace(scenario, num_requests=requests)
    builder = dict(prov.get("builder") or {})
    base_overrides = dict(builder.pop("serving_overrides", {}) or {})

    # run_scenario force-enables the process tracer (its span-derived
    # latency section needs it) and each verification clears the ring to
    # judge its own counters — restore the caller's enabled state after,
    # so a long-lived process doesn't keep paying emit cost forever
    tracer_was_enabled = get_tracer().enabled
    try:
        _verify_all(proposals, scenario, builder, base_overrides,
                    build_server, requests, verifications)
    finally:
        get_tracer().configure(enabled=tracer_was_enabled)

    persist_serve_verifications(results_dir, plan, verifications)
    if artifact_path is not None:
        try:   # write the verdicts back into the artifact for env_report
            plan["verifications"] = verifications
            with open(artifact_path, "w") as f:
                json.dump(plan, f, indent=2)
                f.write("\n")
        except OSError:
            logger.exception("serve_verify: cannot update artifact %s",
                             artifact_path)
    return verifications


def _verify_all(proposals, scenario, builder, base_overrides, build_server,
                requests, verifications) -> None:
    from deepspeed_tpu.serving import bench_serve
    from deepspeed_tpu.telemetry.tracer import get_tracer

    for p in proposals:
        overrides = (p.get("overrides") or {}).get("serving")
        pred = dict(p.get("predicted") or {})
        row: Dict[str, Any] = {"proposal": p.get("id"),
                               "overrides": p.get("overrides"),
                               "predicted": pred}
        if not overrides or scenario is None:
            row["verdict"] = "unverified"
            row["detail"] = ("no executable serving override" if not
                            overrides else "plan has no bench_serve "
                            "provenance (re-run bench_serve --json to "
                            "attach the preset/seed)")
            verifications.append(row)
            continue
        merged = {**base_overrides, **overrides}
        try:
            factory = build_server or bench_serve.build_tiny_server
            server = factory(serving_overrides=merged, **builder).start()
            try:
                # each verification run judges ITS OWN spans/counters: the
                # bounded ring must not leak the baseline run's (or the
                # previous proposal's) request spans into this report
                get_tracer().clear()
                rerun = bench_serve.run_scenario(server, scenario)
            finally:
                server.stop(drain_timeout=30.0)
        except Exception as e:
            logger.exception("serve_verify: re-run for %s failed",
                             p.get("id"))
            row["verdict"] = "unverified"
            row["detail"] = f"re-run failed: {e!r}"
            verifications.append(row)
            continue
        counter = pred.get("counter")
        op = _OPS.get(pred.get("op", ""))
        observed = (_lookup_counter(rerun, counter)
                    if counter is not None else None)
        if op is None or observed is None:
            row["verdict"] = "unverified"
            row["detail"] = (f"counter {counter!r} not in the re-run "
                             "report" if op is not None else
                             f"unknown predicate op {pred.get('op')!r}")
            verifications.append(row)
            continue
        value = float(pred.get("value", 0))
        ok = op(observed, value)
        row["observed"] = {counter: observed}
        row["verdict"] = "verified" if ok else "refuted"
        row["detail"] = (f"{counter} {observed:g} {pred['op']} {value:g} "
                         f"{'holds' if ok else 'FAILS'} (baseline "
                         f"{pred.get('baseline')})")
        if not ok:
            logger.warning("serve_verify: prediction REFUTED for %s: %s",
                           p.get("id"), row["detail"])
        verifications.append(row)


def persist_serve_verifications(results_dir: Optional[str], plan: dict,
                                verifications: List[Dict[str, Any]]) -> None:
    """Merge the verdicts under ``plan.serve_verifications`` in
    ``autotuning_results.json`` — never clobbering an existing training
    tune's experiments/verifications in the same results dir."""
    if not results_dir:
        return
    os.makedirs(results_dir, exist_ok=True)
    path = os.path.join(results_dir, RESULTS_NAME)
    data: Dict[str, Any] = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            logger.warning("serve_verify: existing %s unreadable — "
                           "rewriting", path)
            data = {}
    section = data.setdefault("plan", {})
    section["serve_source"] = plan.get("source")
    section["serve_verifications"] = verifications
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    logger.info(f"serve plan verdicts written to {path}")
