"""Autotuning subsystem (reference: ``deepspeed/autotuning/``)."""

from deepspeed_tpu.autotuning.autotuner import Autotuner, estimate_state_bytes  # noqa: F401
from deepspeed_tpu.autotuning.scheduler import ExperimentRunner, merge_config  # noqa: F401
from deepspeed_tpu.autotuning.tuner import (  # noqa: F401
    BaseTuner,
    CostModel,
    Experiment,
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
)
