"""Experiment runners: in-process (fast) and process-isolated (robust).

Reference analog: ``deepspeed/autotuning/scheduler.py`` — ``ResourceManager`` launches
each candidate config as a separate multi-node job via the launcher
(``scheduler.py:414 _launch_exp``) and scrapes metric files the exit hook writes; a
candidate that OOMs or hangs dies in its own job without killing the tuner.

TPU redesign: an experiment is a fresh engine built from (base config ⊕ overrides) and
timed. ``ExperimentRunner`` does it in-process — SPMD means one process sees the whole
mesh, so there is no job launch / ssh layer to orchestrate; catchable failures
(RESOURCE_EXHAUSTED, compile errors) are recorded per-experiment. But the failures
autotuning exists to find include UNcatchable ones — a hard device OOM that kills the
process, a >20-minute XLA compile — so ``ProcessIsolatedRunner`` runs each candidate
in a fresh subprocess with a timeout, like the reference's launched experiments: the
child dies or is killed, the tuner records ``oom``/``timeout``/``failed`` and moves on.
"""

import copy
import json
import os
import subprocess
import sys
import time
from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.autotuning.tuner import Experiment
from deepspeed_tpu.utils.logging import logger


def merge_config(base: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive dict merge, overrides win (reference: autotuner replaces whole
    sections; nested merge lets overrides stay minimal)."""
    out = copy.deepcopy(base)
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_config(out[k], v)
        else:
            out[k] = v
    return out


class ExperimentRunner:
    """Builds an engine per experiment and measures step time.

    ``batch_fn(global_batch_size) -> batch`` supplies data shaped for the candidate's
    batch size. Metrics recorded: ``latency`` (s/step) and ``throughput``
    (samples/s).

    ``trace_counters=True`` (the plan-verification mode — see
    ``Autotuner(plan=...)``) additionally runs the measured segment under
    the dstrace tracer and records deterministic span counts:
    ``trace_dispatch_spans`` (steps actually dispatched),
    ``trace_drain_spans`` (readback transfers — the async ring's
    designated ``device_get``s, including the closing flush), and
    ``trace_h2d_spans``. These are the counters profile-guided proposals
    are verified against on hosts where wall-clock A/B is noise.
    """

    METRICS = ("latency", "throughput")

    def __init__(self, model, batch_fn: Callable[[int], Any],
                 base_config: Dict[str, Any], mesh=None,
                 loss_fn: Optional[Callable] = None,
                 warmup_steps: int = 1, measure_steps: int = 3,
                 trace_counters: bool = False):
        self.model = model
        self.batch_fn = batch_fn
        self.base_config = base_config
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.trace_counters = trace_counters

    def __call__(self, exp: Experiment) -> Experiment:
        import deepspeed_tpu  # late import: avoid cycle at package init

        exp.status = "running"
        cfg = merge_config(self.base_config, exp.overrides)
        # autotuner owns the batch triple: derive train_batch from mbs x gas x dp
        cfg.pop("train_batch_size", None)
        engine = None
        tracer = None
        tracer_was_enabled = False
        if self.trace_counters:
            from deepspeed_tpu.telemetry import get_tracer
            tracer = get_tracer()
            tracer_was_enabled = tracer.enabled
            tracer.configure(enabled=True)
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, config=cfg, mesh=self.mesh,
                loss_fn=self.loss_fn,
                example_batch=self.batch_fn(1))
            batch = self.batch_fn(engine.train_batch_size)
            for _ in range(self.warmup_steps):
                engine.train_batch(batch=batch)
            if hasattr(engine, "flush_metrics"):
                engine.flush_metrics()   # ring empty: exact drain counting
            jax.block_until_ready(engine.state.params)
            mark = _last_event_id(tracer)
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                loss = engine.train_batch(batch=batch)
            jax.block_until_ready(loss)
            dt = (time.perf_counter() - t0) / self.measure_steps
            exp.metrics = {
                "latency": dt,
                "throughput": engine.train_batch_size / dt,
                "train_batch_size": float(engine.train_batch_size),
            }
            if tracer is not None:
                # the closing flush is the measured segment's final
                # readback transfer — count it, don't time it
                if hasattr(engine, "flush_metrics"):
                    engine.flush_metrics()
                exp.metrics.update(_span_counts(tracer, mark))
            exp.status = "done"
        except Exception as e:  # noqa: BLE001 — any candidate may legally fail
            from deepspeed_tpu.telemetry.memory import is_oom_message
            msg = str(e)
            exp.error = msg
            oom = is_oom_message(msg)
            exp.status = "oom" if oom else "failed"
            if oom:
                # forensics, not just a string match: the live device stats
                # at death, the candidate's analytic ledger, and the
                # observed peak as a first-class metric — what the next
                # sweep iteration prunes against
                exp.memory = _oom_forensics(cfg, engine)
                peak = exp.memory.get("peak_bytes_in_use")
                if peak:
                    exp.metrics = dict(exp.metrics or {},
                                       peak_bytes_in_use=float(peak))
            logger.warning(f"autotuning experiment {exp.name} {exp.status}: "
                           f"{msg.splitlines()[0] if msg else e!r}")
        finally:
            if tracer is not None and not tracer_was_enabled:
                tracer.configure(enabled=False)
        return exp


def _oom_forensics(cfg: Dict[str, Any], engine=None) -> Dict[str, Any]:
    """What an oom-classified experiment records beyond the string match:
    live device/host stats at death, the candidate config's analytic dsmem
    ledger (engine-exact when the engine got built, config-only when init
    itself OOMed), and the observed peak bytes."""
    out: Dict[str, Any] = {}
    try:
        from deepspeed_tpu.utils.memory import get_memory_stats
        stats = get_memory_stats()
        out["stats"] = stats
        out["peak_bytes_in_use"] = int(max(
            (s.get("peak_bytes_in_use_gb", 0.0) * 1e9
             for d, s in stats.items() if d != "host"), default=0))
    except Exception:
        logger.exception("autotuning: oom memory stats capture failed")
    try:
        if engine is not None and hasattr(engine, "memory_ledger"):
            out["ledger"] = engine.memory_ledger().to_dict()
        else:
            from deepspeed_tpu.telemetry.memory import MemoryLedger
            out["ledger"] = MemoryLedger.from_config(
                cfg, num_params=0).to_dict()
            out["ledger"]["notes"].append(
                "engine never constructed (init-time OOM): ledger built "
                "from config only, num_params unknown")
    except Exception:
        logger.exception("autotuning: oom ledger capture failed")
    return out


def _last_event_id(tracer) -> int:
    """High-water event id of the tracer ring (0 when disabled/empty) —
    the measured-segment boundary for ``_span_counts``."""
    if tracer is None:
        return 0
    from deepspeed_tpu.telemetry.tracer import _EID
    snap = tracer.events_snapshot()
    return max((e[_EID] for e in snap), default=0)


def _span_counts(tracer, mark: int) -> Dict[str, float]:
    """Deterministic span counters over events emitted after ``mark``."""
    from deepspeed_tpu.telemetry.tracer import _EID, _NAME, _PH
    counts = {"engine/dispatch": 0, "engine/train_step": 0,
              "engine/drain": 0, "comm/h2d": 0}
    for e in tracer.events_snapshot():
        if e[_EID] > mark and e[_PH] == "X" and e[_NAME] in counts:
            counts[e[_NAME]] += 1
    return {
        "trace_dispatch_spans": float(counts["engine/dispatch"]
                                      + counts["engine/train_step"]),
        "trace_drain_spans": float(counts["engine/drain"]),
        "trace_h2d_spans": float(counts["comm/h2d"]),
    }


_EXP_BOOTSTRAP = r"""
import importlib, json, os, sys
for p in os.environ.get("DSTPU_TUNE_PATH", "").split(os.pathsep):
    if p and p not in sys.path:
        sys.path.insert(0, p)
if os.environ.get("DSTPU_TUNE_CPU_DEVICES"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices",
                      int(os.environ["DSTPU_TUNE_CPU_DEVICES"]))
mod_name, _, qual = os.environ["DSTPU_TUNE_FACTORY"].partition(":")
factory = importlib.import_module(mod_name)
for part in qual.split("."):
    factory = getattr(factory, part)
spec = factory()
from deepspeed_tpu.autotuning.scheduler import ExperimentRunner
from deepspeed_tpu.autotuning.tuner import Experiment
runner = ExperimentRunner(
    spec["model"], spec["batch_fn"],
    json.loads(os.environ["DSTPU_TUNE_BASE"]),
    mesh=spec.get("mesh"), loss_fn=spec.get("loss_fn"),
    warmup_steps=int(os.environ["DSTPU_TUNE_WARMUP"]),
    measure_steps=int(os.environ["DSTPU_TUNE_MEASURE"]))
exp = runner(Experiment(os.environ["DSTPU_TUNE_NAME"],
                        json.loads(os.environ["DSTPU_TUNE_OVERRIDES"])))
print("DSTPU_EXP_RESULT " + json.dumps(
    {"status": exp.status, "metrics": exp.metrics, "error": exp.error,
     "memory": exp.memory}),
    flush=True)
"""


class ProcessIsolatedRunner:
    """Runs each candidate in a fresh subprocess with a timeout (reference:
    ``scheduler.py:414 _launch_exp`` — experiments are separate jobs that can
    die without killing the tuner).

    ``model_factory``: importable ``"module:qualname"`` (or module-level
    callable) returning ``{"model", "batch_fn", "loss_fn"?, "mesh"?}`` —
    rebuilt inside each child so no live objects cross the process boundary.
    The experiment name/overrides ride in env vars (``DSTPU_TUNE_NAME``/
    ``DSTPU_TUNE_OVERRIDES``). A child that is killed by a hard device OOM
    records ``oom``; one that exceeds ``timeout`` (e.g. a pathological XLA
    compile) is killed and records ``timeout``; both are infeasible, the
    sweep continues.
    """

    METRICS = ExperimentRunner.METRICS

    def __init__(self, model_factory, base_config: Dict[str, Any],
                 warmup_steps: int = 1, measure_steps: int = 3,
                 timeout: float = 600.0, cpu_devices: Optional[int] = None,
                 child_env: Optional[Dict[str, str]] = None):
        self._extra_paths = []
        if callable(model_factory):
            mod = getattr(model_factory, "__module__", None)
            qual = getattr(model_factory, "__qualname__", None)
            if not mod or not qual or "<locals>" in qual:
                raise ValueError("model_factory must be importable "
                                 "(module-level) to run in a child process")
            if "." not in mod:
                # top-level module (e.g. a pytest-loaded test file): make its
                # directory importable in the child (as testing.py does)
                mod_file = getattr(sys.modules.get(mod), "__file__", None)
                if mod_file:
                    self._extra_paths.append(
                        os.path.dirname(os.path.abspath(mod_file)))
            model_factory = f"{mod}:{qual}"
        self.model_factory = model_factory
        self.base_config = base_config
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.timeout = timeout
        self.cpu_devices = cpu_devices
        self.child_env = child_env or {}
        self.mesh = None   # no parent-side mesh; Autotuner falls back to the
        # mesh it was constructed with for stage-feasibility pruning

    def __call__(self, exp: Experiment) -> Experiment:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ,
                   DSTPU_TUNE_FACTORY=self.model_factory,
                   DSTPU_TUNE_BASE=json.dumps(self.base_config),
                   DSTPU_TUNE_NAME=exp.name,
                   DSTPU_TUNE_OVERRIDES=json.dumps(exp.overrides),
                   DSTPU_TUNE_WARMUP=str(self.warmup_steps),
                   DSTPU_TUNE_MEASURE=str(self.measure_steps),
                   DSTPU_TUNE_PATH=os.pathsep.join(
                       [repo_root, *self._extra_paths]),
                   **self.child_env)
        if self.cpu_devices:
            env["DSTPU_TUNE_CPU_DEVICES"] = str(self.cpu_devices)
        exp.status = "running"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _EXP_BOOTSTRAP], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                timeout=self.timeout, cwd=repo_root)
            out = proc.stdout or ""
        except subprocess.TimeoutExpired as e:
            out = (e.stdout or b"")
            out = out.decode() if isinstance(out, bytes) else out
            tail = "\n".join(out.splitlines()[-5:])
            exp.status = "timeout"
            exp.error = (f"experiment exceeded {self.timeout}s "
                         "(hung compile or runaway candidate); child killed; "
                         f"tail:\n{tail}")
            logger.warning(f"autotuning experiment {exp.name} timed out "
                           f"after {self.timeout}s — recorded infeasible")
            return exp
        for line in out.splitlines():
            if line.startswith("DSTPU_EXP_RESULT "):
                res = json.loads(line[len("DSTPU_EXP_RESULT "):])
                exp.status = res["status"]
                exp.metrics = res["metrics"]
                exp.error = res["error"]
                exp.memory = res.get("memory")
                return exp
        # child died before reporting (hard OOM kill, segfault, ...)
        from deepspeed_tpu.telemetry.memory import is_oom_message
        tail = "\n".join(out.splitlines()[-5:])
        oom = is_oom_message(out) or proc.returncode in (-9, 137)
        exp.status = "oom" if oom else "failed"
        if oom:
            # the child is gone: no in-process stats to read, but the
            # candidate's analytic ledger is still computable parent-side
            exp.memory = _oom_forensics(
                merge_config(self.base_config, exp.overrides))
            exp.memory["note"] = ("child killed before reporting — stats "
                                  "are the PARENT process's, ledger is the "
                                  "candidate's analytic plan")
        exp.error = (f"child exited {proc.returncode} without reporting; "
                     f"tail:\n{tail}")
        logger.warning(f"autotuning experiment {exp.name} child died "
                       f"(rc={proc.returncode}) — recorded {exp.status}")
        return exp
