"""In-process experiment runner.

Reference analog: ``deepspeed/autotuning/scheduler.py`` — ``ResourceManager`` launches
each candidate config as a separate multi-node job via the launcher and scrapes metric
files the exit hook writes.

TPU redesign: an experiment is a fresh engine built from (base config ⊕ overrides) and
timed in-process — SPMD means one process sees the whole mesh, so there is no job
launch / ssh layer to orchestrate. OOM (RESOURCE_EXHAUSTED) and compile failures are
caught per-experiment and recorded, mirroring the reference's failed-experiment
bookkeeping, so a failing candidate never kills the sweep.
"""

import copy
import time
from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.autotuning.tuner import Experiment
from deepspeed_tpu.utils.logging import logger


def merge_config(base: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive dict merge, overrides win (reference: autotuner replaces whole
    sections; nested merge lets overrides stay minimal)."""
    out = copy.deepcopy(base)
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_config(out[k], v)
        else:
            out[k] = v
    return out


class ExperimentRunner:
    """Builds an engine per experiment and measures step time.

    ``batch_fn(global_batch_size) -> batch`` supplies data shaped for the candidate's
    batch size. Metrics recorded: ``latency`` (s/step) and ``throughput``
    (samples/s).
    """

    METRICS = ("latency", "throughput")

    def __init__(self, model, batch_fn: Callable[[int], Any],
                 base_config: Dict[str, Any], mesh=None,
                 loss_fn: Optional[Callable] = None,
                 warmup_steps: int = 1, measure_steps: int = 3):
        self.model = model
        self.batch_fn = batch_fn
        self.base_config = base_config
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps

    def __call__(self, exp: Experiment) -> Experiment:
        import deepspeed_tpu  # late import: avoid cycle at package init

        exp.status = "running"
        cfg = merge_config(self.base_config, exp.overrides)
        # autotuner owns the batch triple: derive train_batch from mbs x gas x dp
        cfg.pop("train_batch_size", None)
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, config=cfg, mesh=self.mesh,
                loss_fn=self.loss_fn,
                example_batch=self.batch_fn(1))
            batch = self.batch_fn(engine.train_batch_size)
            for _ in range(self.warmup_steps):
                engine.train_batch(batch=batch)
            jax.block_until_ready(engine.state.params)
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                loss = engine.train_batch(batch=batch)
            jax.block_until_ready(loss)
            dt = (time.perf_counter() - t0) / self.measure_steps
            exp.metrics = {
                "latency": dt,
                "throughput": engine.train_batch_size / dt,
                "train_batch_size": float(engine.train_batch_size),
            }
            exp.status = "done"
        except Exception as e:  # noqa: BLE001 — any candidate may legally fail
            msg = str(e)
            exp.error = msg
            oom = ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                   or "out of memory" in msg)
            exp.status = "oom" if oom else "failed"
            logger.warning(f"autotuning experiment {exp.name} {exp.status}: "
                           f"{msg.splitlines()[0] if msg else e!r}")
        return exp
