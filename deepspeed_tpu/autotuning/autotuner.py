"""Autotuner orchestration.

Reference analog: ``deepspeed/autotuning/autotuner.py:42`` — explores (ZeRO stage,
micro-batch size, offload/bucket knobs) to maximize throughput: estimates per-stage
memory feasibility (``_get_gpu_memory_per_stage``), probes the max micro-batch size,
then hands candidate configs to a tuner strategy and launches experiments.

TPU redesign: the knob space is (zero stage, micro-batch, remat) — bucket sizes,
overlap flags, and fetch thresholds don't exist because XLA schedules the collectives.
Memory feasibility uses an analytic HBM model (params/grads/optimizer-state bytes per
sharding stage) plus XLA's ``memory_analysis`` when a candidate compiles. Experiments
run in-process by default; ``isolation="process"`` runs each candidate in its own
subprocess with a timeout so hard OOM kills and hung compiles are recorded as
infeasible instead of killing the tune (see scheduler.py ProcessIsolatedRunner).
"""

import json
import math
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.autotuning.scheduler import ExperimentRunner, merge_config
from deepspeed_tpu.autotuning.tuner import (
    BaseTuner,
    Experiment,
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
)
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.utils.logging import logger

DEFAULT_MIN_MBS = 1
TUNER_CLASSES = {
    "gridsearch": GridSearchTuner,
    "random": RandomTuner,
    "model_based": ModelBasedTuner,
}
#: keys accepted in the ds-config "autotuning" group (reference:
#: autotuning/config.py DeepSpeedAutotuningConfig — subset meaningful here)
_AUTOTUNING_GROUP_KEYS = frozenset({
    "enabled", "metric", "tuner_type", "zero_stages", "max_micro_batch",
    "num_micro_batches", "try_remat", "try_offload", "num_tuning_trials",
    "early_stopping", "results_dir",
})


def estimate_state_bytes(n_params: int, stage: int, fsdp_size: int,
                         compute_bytes: int = 2,
                         offload_optimizer: bool = False) -> int:
    """Analytic per-device bytes for params + grads + Adam states under a ZeRO stage
    (reference: autotuner.py get_instantiation_memory_required_per_gpu).

    stage 0: everything replicated; 1: optimizer states sharded; 2: +grads sharded;
    3: +params sharded. Optimizer master+moments = 3 x fp32;
    ``offload_optimizer`` moves them (and the fp32 grad buffer — the host
    path accumulates compute-dtype grads) to the host tier.
    """
    opt = 12 * n_params  # fp32 master + m + v
    grads = 4 * n_params  # fp32 grad accumulation
    params = compute_bytes * n_params
    if stage >= 1:
        opt //= fsdp_size
    if stage >= 2:
        grads //= fsdp_size
    if stage >= 3:
        params //= fsdp_size
    if offload_optimizer:
        opt = 0
        grads = compute_bytes * n_params // (fsdp_size if stage >= 2 else 1)
    return params + grads + opt


class Autotuner:
    """Find the best (zero stage, micro batch) config for a model on this mesh.

    Usage::

        tuner = Autotuner(model, base_config, batch_fn=random_batch)
        best_config, best_metrics = tuner.tune()
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_fn: Callable[[int], Any], mesh=None,
                 loss_fn: Optional[Callable] = None,
                 example_batch: Any = None,
                 metric: str = "throughput",
                 tuner_type: str = "model_based",
                 zero_stages: Optional[List[int]] = None,
                 max_micro_batch: int = 64,
                 num_micro_batches: int = 4,
                 try_remat: bool = False,
                 try_offload: Optional[bool] = None,
                 warmup_steps: int = 1, measure_steps: int = 3,
                 n_trials: int = 50, early_stopping: int = 0,
                 results_dir: Optional[str] = None,
                 hbm_bytes: Optional[int] = None,
                 isolation: str = "in_process",
                 model_factory=None,
                 experiment_timeout: float = 600.0,
                 isolation_cpu_devices: Optional[int] = None,
                 plan: Any = None):
        self.model = model
        self.base_config = dict(base_config)
        # the ds-config "autotuning" group configures the tuner exactly like
        # the reference (single-JSON contract: one config drives engine AND
        # tuner); group values override the constructor defaults for any
        # knob the caller did not set in the config dict itself
        at = self.base_config.get(C.AUTOTUNING)
        if at is None:
            at = {}
        elif isinstance(at, bool):
            at = {"enabled": at}      # `"autotuning": false` shorthand
        elif not isinstance(at, dict):
            raise ValueError(
                f'config "{C.AUTOTUNING}" group must be a dict or bool '
                f'(e.g. {{"enabled": true, "metric": "throughput"}}), '
                f"got {type(at).__name__}: {at!r}")
        unknown = set(at) - _AUTOTUNING_GROUP_KEYS
        if unknown:
            logger.warning(f"autotuning config group: unknown keys "
                           f"{sorted(unknown)} ignored "
                           f"(known: {sorted(_AUTOTUNING_GROUP_KEYS)})")
        # "enabled": false turns tune() into a pass-through (reference: the
        # launcher consults autotuning.enabled before tuning) — porting a
        # reference config with tuning switched off must not burn trials
        self.enabled = bool(at.get("enabled", True))
        metric = at.get("metric", metric)
        if metric not in ExperimentRunner.METRICS:
            raise ValueError(f"unknown autotuning metric {metric!r}; "
                             f"one of {ExperimentRunner.METRICS}")
        self.metric = metric
        self.tuner_type = at.get("tuner_type", tuner_type)
        zero_stages = at.get("zero_stages", zero_stages)
        self.zero_stages = zero_stages if zero_stages is not None else [0, 1, 2, 3]
        self.max_micro_batch = int(at.get("max_micro_batch", max_micro_batch))
        self.num_micro_batches = int(at.get("num_micro_batches",
                                            num_micro_batches))
        self.try_remat = bool(at.get("try_remat", try_remat))
        # None = auto: offload variants only where nothing fits in HBM
        self.try_offload = at.get("try_offload", try_offload)
        self.n_trials = int(at.get("num_tuning_trials", n_trials))
        self.early_stopping = int(at.get("early_stopping", early_stopping))
        self.results_dir = at.get("results_dir", results_dir)
        self.hbm_bytes = hbm_bytes
        self._prune_mesh = mesh   # stage-feasibility pruning (tune()) even
        if isolation == "process":  # when experiments run in children
            # each candidate in its own subprocess with a timeout — survives
            # hard OOM kills and pathological compiles (reference:
            # scheduler.py:414 _launch_exp); needs an importable factory
            from deepspeed_tpu.autotuning.scheduler import (
                ProcessIsolatedRunner)
            if model_factory is None:
                raise ValueError("isolation='process' requires model_factory "
                                 "(importable 'module:qualname' rebuilding "
                                 "the model in each child)")
            if loss_fn is not None:
                raise ValueError("isolation='process' ignores loss_fn — "
                                 "return it from model_factory instead "
                                 "(it cannot cross the process boundary)")
            self.runner = ProcessIsolatedRunner(
                model_factory, self.base_config,
                warmup_steps=warmup_steps, measure_steps=measure_steps,
                timeout=experiment_timeout,
                cpu_devices=isolation_cpu_devices)
        elif isolation == "in_process":
            self.runner = ExperimentRunner(
                model, batch_fn, self.base_config, mesh=mesh, loss_fn=loss_fn,
                warmup_steps=warmup_steps, measure_steps=measure_steps)
        else:
            raise ValueError(f"unknown isolation {isolation!r}; "
                             "'in_process' or 'process'")
        # lazy: building an example batch may touch the device runtime, and
        # with isolation='process' the parent must NOT claim the (exclusive)
        # TPU before its experiment children do
        self._example_batch = example_batch
        self._batch_fn = batch_fn
        self.records: List[Experiment] = []
        # profile-guided mode (``dstpu plan`` -> Autotuner): a plan report
        # (dict), its artifact path, or a trace path replaces the blind
        # search space — tune() executes ONLY the plan's proposals and
        # verifies each prediction against the resulting trace counters
        self.plan = self._load_plan(plan) if plan is not None else None
        self.plan_verifications: List[Dict[str, Any]] = []

    @staticmethod
    def _load_plan(plan: Any) -> Dict[str, Any]:
        """Accept a plan report dict, a plan-artifact JSON path, or a raw
        dstrace dump path (attributed on the fly)."""
        if isinstance(plan, dict):
            if "proposals" not in plan:
                raise ValueError("plan dict has no 'proposals' — pass the "
                                 "report `dstpu plan --out` writes (or a "
                                 "trace path to attribute here)")
            return plan
        if isinstance(plan, str):
            from deepspeed_tpu.telemetry import attribution
            with open(plan) as f:
                obj = json.load(f)
            if isinstance(obj, dict) and "proposals" in obj:
                return obj                       # plan artifact
            return attribution.attribute(        # raw trace dump
                attribution.events_from_chrome(obj), source=plan)
        raise ValueError(f"plan must be a report dict or path, "
                         f"got {type(plan).__name__}")

    # ------------------------------------------------------------------
    def model_info(self) -> Dict[str, Any]:
        """Param count without materializing weights (reference: autotuner
        ``_generate_experiments`` model info probe)."""
        if not hasattr(self.model, "init"):
            return {"num_params": 0}
        if self._example_batch is None:
            self._example_batch = self._batch_fn(1)
        shapes = jax.eval_shape(
            lambda r: self.model.init(r, self._example_batch),
            jax.random.PRNGKey(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes))
        return {"num_params": n}

    def feasible_stages(self, fsdp_size: int) -> List[int]:
        """Prune stages whose *static* state already exceeds HBM (analytic)."""
        if not self.hbm_bytes:
            return list(self.zero_stages)
        n = self.model_info()["num_params"]
        keep = [s for s in self.zero_stages
                if estimate_state_bytes(n, s, fsdp_size) < self.hbm_bytes]
        return keep or [max(self.zero_stages)]

    def feasible_configs(self, fsdp_size: int) -> List[Tuple[int, bool]]:
        """(stage, offload_optimizer) candidates: stages feasible in-HBM run
        plain (+offloaded too when try_offload); stages feasible ONLY with
        the host optimizer tier enter the space offloaded — the reference
        autotuner's offloading dimension (autotuning/config.py)."""
        if not self.hbm_bytes:
            pairs = [(s, False) for s in self.zero_stages]
            if self.try_offload:
                pairs += [(s, True) for s in self.zero_stages]
            return pairs
        n = self.model_info()["num_params"]
        pairs = []
        for s in self.zero_stages:
            if estimate_state_bytes(n, s, fsdp_size) < self.hbm_bytes:
                pairs.append((s, False))
                if self.try_offload:
                    pairs.append((s, True))
            elif self.try_offload is not False and estimate_state_bytes(
                    n, s, fsdp_size, offload_optimizer=True) < self.hbm_bytes:
                pairs.append((s, True))   # only fits with the host tier
        return pairs or [(max(self.zero_stages), True)]

    def _mbs_candidates(self) -> List[int]:
        """Log-spaced micro-batch sizes up to max (reference:
        _get_min_micro_batch_size/_get_max_micro_batch_size probe then interpolate)."""
        cands = []
        m = DEFAULT_MIN_MBS
        while m <= self.max_micro_batch:
            cands.append(m)
            m *= 2
        if len(cands) > self.num_micro_batches:
            idx = np.linspace(0, len(cands) - 1, self.num_micro_batches)
            cands = [cands[int(round(i))] for i in idx]
        return sorted(set(cands))

    def generate_experiments(self, stages) -> List[Experiment]:
        exps = []
        for entry in stages:
            stage, offload = entry if isinstance(entry, tuple) else (entry,
                                                                     False)
            for mbs in self._mbs_candidates():
                variants = [False, True] if self.try_remat else [False]
                for remat in variants:
                    name = f"z{stage}_mbs{mbs}" + ("_remat" if remat else "") \
                        + ("_off" if offload else "")
                    zero: Dict[str, Any] = {"stage": stage}
                    if offload:
                        zero["offload_optimizer"] = {"device": "cpu"}
                    ov: Dict[str, Any] = {
                        "zero_optimization": zero,
                        "train_micro_batch_size_per_gpu": mbs,
                        "gradient_accumulation_steps":
                            self.base_config.get("gradient_accumulation_steps", 1),
                    }
                    if remat:
                        ov["activation_checkpointing"] = {"policy": "nothing_saveable"}
                    exps.append(Experiment(name, ov))
        return exps

    # ------------------------------------------------------------------
    def tune(self) -> Tuple[Optional[Dict[str, Any]], Dict[str, float]]:
        if not self.enabled:
            logger.info("autotuning: disabled via the config group "
                        "(autotuning.enabled=false); returning base config "
                        "unchanged")
            return dict(self.base_config), {}
        if self.plan is not None:
            return self.tune_from_plan()
        fsdp = 1
        mesh = getattr(self.runner, "mesh", None) or self._prune_mesh
        if mesh is not None:
            fsdp = int(np.prod([mesh.shape.get(a, 1)
                                for a in ("fsdp_out", "fsdp", "data")]))
        stages = self.feasible_configs(fsdp)
        exps = self.generate_experiments(stages)
        logger.info(f"autotuning: {len(exps)} candidates over "
                    f"(stage, offload) {stages}, "
                    f"metric={self.metric}, tuner={self.tuner_type}")
        tuner_cls = TUNER_CLASSES.get(self.tuner_type)
        if tuner_cls is None:
            raise ValueError(f"unknown tuner {self.tuner_type!r}; "
                             f"one of {sorted(TUNER_CLASSES)}")
        higher = self.metric != "latency"
        tuner: BaseTuner = tuner_cls(exps, self.runner, metric=self.metric,
                                     higher_is_better=higher)
        best = tuner.tune(n_trials=self.n_trials,
                          early_stopping=self.early_stopping)
        self.records = tuner.records
        self._write_results(best)
        if best is None:
            return None, {}
        best_config = merge_config(self.base_config, best.overrides)
        return best_config, dict(best.metrics)

    # ------------------------------------------------------------------
    # profile-guided mode: execute ONLY the plan's proposals, verify the
    # predicted win against the resulting trace (the telemetry->plan->
    # config loop; DeepCompile idiom, arxiv 2504.09983)
    # ------------------------------------------------------------------
    def tune_from_plan(self) -> Tuple[Optional[Dict[str, Any]],
                                      Dict[str, float]]:
        proposals = [p for p in self.plan.get("proposals", [])
                     if p.get("overrides")]
        advisory = [p["id"] for p in self.plan.get("proposals", [])
                    if not p.get("overrides")]
        if advisory:
            logger.info(f"autotuning(plan): advisory proposals "
                        f"{advisory} carry no executable overrides — "
                        "skipped (model/runner-bound knobs)")
        if not proposals:
            logger.info("autotuning(plan): no executable proposals in the "
                        "plan; returning base config unchanged")
            return dict(self.base_config), {}
        # trace-derived counters need an in-process tracer; the process-
        # isolated runner can't see its children's rings, so predictions
        # there are recorded unverified rather than guessed at
        can_verify = isinstance(self.runner, ExperimentRunner)
        if can_verify:
            counters_were_on = self.runner.trace_counters
            self.runner.trace_counters = True
        self.records = []
        self.plan_verifications = []
        best: Optional[Experiment] = None
        higher = self.metric != "latency"
        for p in proposals:
            exp = Experiment(f"plan_{p['id']}", p["overrides"])
            self.runner(exp)
            self.records.append(exp)
            self.plan_verifications.append(self._verify_proposal(p, exp))
            v = exp.metric(self.metric)
            if exp.status == "done" and v is not None and (
                    best is None or
                    (v > best.metrics[self.metric]) == higher):
                best = exp
        if can_verify:
            self.runner.trace_counters = counters_were_on
        self._write_results(best)
        if best is None:
            return None, {}
        return merge_config(self.base_config, best.overrides), \
            dict(best.metrics)

    def _verify_proposal(self, proposal: Dict[str, Any],
                         exp: Experiment) -> Dict[str, Any]:
        """Check the proposal's prediction against what the experiment's
        trace actually recorded. ``readback_transfers`` is the fully
        deterministic one: executing N steps under ``sync_every=k`` must
        produce exactly ceil(N/k) ``engine/drain`` spans — counted, not
        timed, so the verdict is exact on any host."""
        pred = dict(proposal.get("predicted", {}))
        out: Dict[str, Any] = {"proposal": proposal["id"],
                               "experiment": exp.name,
                               "status": exp.status,
                               "predicted": pred}
        if exp.status != "done":
            out["verdict"] = "unverified"
            out["detail"] = f"experiment {exp.status}: {exp.error}"
            return out
        if pred.get("metric") == "readback_transfers":
            steps = exp.metrics.get("trace_dispatch_spans")
            drains = exp.metrics.get("trace_drain_spans")
            if steps is None:
                out["verdict"] = "unverified"
                out["detail"] = ("no trace counters (process-isolated "
                                 "runner or tracer unavailable)")
                return out
            se = int(pred["sync_every"])
            expected = math.ceil(int(steps) / se)
            # the counterfactual uses the cadence the PLAN observed (1 in
            # sync mode, the current sync_every for raise_sync_every) over
            # THIS experiment's step count — not the raw step count
            base_se = max(int(pred.get("baseline_sync_every", 1)), 1)
            out["observed"] = {"steps": int(steps),
                               "transfers": int(drains),
                               "transfers_without_plan":
                                   math.ceil(int(steps) / base_se)}
            out["verdict"] = "verified" if int(drains) == expected \
                else "refuted"
            out["detail"] = (f"{int(steps)} steps -> {int(drains)} "
                             f"readback transfers (predicted "
                             f"ceil({int(steps)}/{se}) = {expected})")
            if out["verdict"] == "refuted":
                logger.warning(f"autotuning(plan): prediction REFUTED for "
                               f"{proposal['id']}: {out['detail']}")
            return out
        if pred.get("metric") == "h2d_off_main_track":
            # prefetch moves staging to the worker thread; with batch=
            # experiments the engine stages inline either way, so this
            # prediction needs a data_iter workload — record, don't guess
            out["verdict"] = "unverified"
            out["detail"] = ("prefetch staging only engages on "
                             "train_batch(data_iter=...) workloads; run "
                             "bench.py --prefetch for the A/B")
            return out
        out["verdict"] = "unverified"
        out["detail"] = f"no verifier for metric {pred.get('metric')!r}"
        return out

    def _write_results(self, best: Optional[Experiment]):
        if not self.results_dir or jax.process_index() != 0:
            return
        os.makedirs(self.results_dir, exist_ok=True)
        out = {
            "metric": self.metric,
            "best": None if best is None else
                {"name": best.name, "overrides": best.overrides,
                 "metrics": best.metrics},
            "experiments": [
                {"name": e.name, "status": e.status, "metrics": e.metrics,
                 "overrides": e.overrides, "error": e.error,
                 # dsmem forensics for oom-classified candidates (live
                 # stats + analytic ledger + observed peak)
                 **({"memory": e.memory}
                    if getattr(e, "memory", None) else {})}
                for e in self.records],
        }
        if self.plan_verifications:
            out["plan"] = {"source": self.plan.get("source"),
                           "verifications": self.plan_verifications}
        path = os.path.join(self.results_dir, "autotuning_results.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        logger.info(f"autotuning results written to {path}")
