"""Shared bench-script utilities (stdlib at import time — safe to import
before jax; ``bounded_device_discovery`` pulls in ``comm.guard`` lazily,
inside the call)."""

import datetime
import glob
import json
import os
import sys
import threading

_REPO = os.path.dirname(os.path.abspath(__file__))


def _bench_logs_dir():
    # DSTPU_BENCH_LOGS lets tests point at a hermetic tree.
    return os.environ.get("DSTPU_BENCH_LOGS",
                          os.path.join(_REPO, "bench_logs"))


def _headline_lines(path):
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and {"metric", "value", "unit"} <= set(rec):
                    yield rec
    except OSError:
        return


def latest_banked_result(metric: str = None):
    """Newest parseable headline JSON line under bench_logs/ whose metric
    matches ``metric`` (records for other metrics are REJECTED, never
    substituted — a wedged decode bench must not replay a training number).

    ``bench_logs/latest_headline.json`` (written by every successful
    ``bench.py`` run) wins outright when present and matching. Otherwise
    scans every ``*.json`` for matching headline lines; ties break by file
    mtime (newest first). Returns ``(record, source_path, mtime)`` or
    ``None``.
    """
    logs = _bench_logs_dir()
    canonical = os.path.join(logs, "latest_headline.json")
    for rec in _headline_lines(canonical):
        if metric is None or rec["metric"] == metric:
            return rec, canonical, os.path.getmtime(canonical)
    candidates = []
    for path in glob.glob(os.path.join(logs, "**", "*.json"), recursive=True):
        if path == canonical:
            continue
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        for rec in _headline_lines(path):
            if metric is None or rec["metric"] == metric:
                candidates.append((rec, path, mtime))
    if not candidates:
        return None
    return max(candidates, key=lambda c: c[2])


def bank_headline(record: dict, filename: str = "latest_headline.json"):
    """Persist a successful bench headline as a banked result.

    Best-effort (never fails the bench): writes the line to
    ``bench_logs/<filename>`` so a later wedged-tunnel run can replay it
    with stale provenance (``latest_headline.json`` is the canonical train
    headline; other benches bank under their own names and are found by
    metric match).
    """
    try:
        record = dict(record)
        record.setdefault("measured_at", datetime.datetime.now(
            datetime.timezone.utc).isoformat())
        path = os.path.join(_bench_logs_dir(), filename)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass


def emit_stale_banked(name: str, metric: str = None) -> bool:
    """Print the newest banked headline with stale-provenance fields.

    The round-end driver needs ONE parseable JSON line; when the axon tunnel
    is wedged (BENCH_r02..r04 were all rc=3 empties) the honest fallback is
    the most recent real-chip measurement, explicitly marked stale. Returns
    True if a line was printed.
    """
    found = latest_banked_result(metric)
    if not found:
        return False
    rec, path, mtime = found
    rec = dict(rec)
    rec["stale"] = True
    if "measured_at" not in rec:
        # mtime is the measurement time only for files written in place;
        # a fresh checkout resets it, so label the provenance honestly.
        rec["measured_at"] = datetime.datetime.fromtimestamp(
            mtime, datetime.timezone.utc).isoformat()
        rec["measured_at_source"] = "file_mtime"
    rec["source"] = os.path.relpath(path, _REPO)
    rec["stale_reason"] = f"{name}: TPU device discovery timed out (tunnel wedged)"
    print(json.dumps(rec))
    return True


# distinct from rc 3 (nothing banked) and rc 0 (fresh run): exit status alone
# must never conflate a stale replay with a real measurement
STALE_REPLAY_EXIT_CODE = 7


def guard_device_discovery(name: str, timeout: float = 180.0,
                           stale_metric: str = None):
    """Fail fast if TPU device discovery hangs (wedged axon tunnel, observed
    2026-07-30). A THREAD, not SIGALRM: the hang sits in native PJRT init
    where a python signal handler never runs. Call the returned function
    after ``jax.devices()`` succeeds to disarm.

    When ``stale_metric`` is set (the round-end driver path), a timeout
    emits the newest banked headline for that metric (marked
    ``stale: true``) and exits ``STALE_REPLAY_EXIT_CODE`` (7) so the driver
    records a parseable line while the exit status still says "replay, not
    fresh". Drivers that can only accept rc 0 opt in with
    ``DSTPU_STALE_REPLAY_RC0=1``. Exits 3 when nothing is banked or
    ``stale_metric`` is None.
    """
    discovered = threading.Event()

    def _watchdog():
        if not discovered.wait(timeout):
            print(f"{name}: TPU device discovery exceeded {timeout:.0f}s — "
                  "tunnel wedged", file=sys.stderr)
            if stale_metric is not None and emit_stale_banked(name, stale_metric):
                sys.stdout.flush()
                rc0 = os.environ.get("DSTPU_STALE_REPLAY_RC0", "") not in ("", "0")
                os._exit(0 if rc0 else STALE_REPLAY_EXIT_CODE)
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()
    return discovered.set


# classified discovery exit codes (distinct from rc 3 "wedged, nothing
# banked" and rc 7 stale replay): the exit status alone names the failure
# family, so a BENCH driver log is a diagnosis even when stderr was lost
DISCOVERY_NO_DEVICES_EXIT_CODE = 4
DISCOVERY_AUTH_EXIT_CODE = 5


def bounded_device_discovery(name, timeout=180.0, retries=2, backoff_s=2.0,
                             stale_metric=None, devices_fn=None):
    """TPU device discovery under ``comm.guard.bounded_init`` — the
    wedge-proof replacement for ``guard_device_discovery``.

    Runs ``jax.devices()`` on a watched thread with a deadline and
    exponential-backoff retries for TRANSIENT control-plane failures
    (coordinator not up, connection refused/reset), then exits with a
    distinct rc and a ONE-LINE stderr diagnosis instead of ever hanging:

      tunnel wedge   no response inside ``timeout`` (or transient retries
                     exhausted) -> stale-replay path when ``stale_metric``
                     is banked (rc 7, or rc 0 under DSTPU_STALE_REPLAY_RC0
                     — unchanged), else rc 3
      auth           credential/permission failure -> rc 5 (never replayed:
                     a stale headline must not paper over a revoked token)
      no devices     backend initialized but found nothing / no backend
                     -> rc 4

    Returns the device list on success. ``devices_fn`` overrides the
    discovery callable for tests.
    """
    from deepspeed_tpu.comm.guard import (CommInitError, CommOutcome,
                                          CommWedgeError, bounded_init)

    if devices_fn is None:
        def devices_fn():
            import jax
            return jax.devices()

    def _hard_exit(rc):
        # after a wedge the discovery worker thread is still stuck inside
        # native PJRT init; interpreter finalization (atexit handlers, jax
        # teardown) can re-wedge on the half-initialized backend — the exact
        # silent BENCH hang this path exists to kill. Flush what the driver
        # reads, then exit without finalization (the old watchdog's os._exit
        # guarantee, kept).
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)

    def _wedge_exit(diagnosis):
        print(f"{name}: device discovery failed: {diagnosis}",
              file=sys.stderr)
        if stale_metric is not None and emit_stale_banked(name, stale_metric):
            rc0 = os.environ.get("DSTPU_STALE_REPLAY_RC0", "") not in ("", "0")
            _hard_exit(0 if rc0 else STALE_REPLAY_EXIT_CODE)
        _hard_exit(3)

    try:
        devices = bounded_init(devices_fn, name=f"{name}_discovery",
                               deadline_s=timeout, retries=retries,
                               backoff_s=backoff_s)
    except CommWedgeError:
        _wedge_exit(f"tunnel wedge — no response from PJRT init in "
                    f"{timeout:.0f}s")
    except CommInitError as e:
        text = repr(e.__cause__ if e.__cause__ is not None else e).lower()
        if any(m in text for m in ("permission", "unauthenticated",
                                   "forbidden", "credential", "oauth",
                                   "authentication")):
            print(f"{name}: device discovery failed: auth — credentials "
                  f"rejected by the control plane ({e.__cause__!r})",
                  file=sys.stderr)
            sys.exit(DISCOVERY_AUTH_EXIT_CODE)
        if e.outcome is CommOutcome.TRANSIENT:
            _wedge_exit(f"tunnel wedge — transient control-plane failures "
                        f"exhausted {e.attempts} attempt(s) "
                        f"({e.__cause__!r})")
        print(f"{name}: device discovery failed: no devices — backend "
              f"init failed ({e.__cause__!r})", file=sys.stderr)
        sys.exit(DISCOVERY_NO_DEVICES_EXIT_CODE)
    if not devices:
        print(f"{name}: device discovery failed: no devices — PJRT "
              f"returned an empty device list", file=sys.stderr)
        sys.exit(DISCOVERY_NO_DEVICES_EXIT_CODE)
    return devices
