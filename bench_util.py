"""Shared bench-script utilities (stdlib only — imported before jax)."""

import os
import sys
import threading


def guard_device_discovery(name: str, timeout: float = 180.0):
    """Fail fast if TPU device discovery hangs (wedged axon tunnel, observed
    2026-07-30). A THREAD, not SIGALRM: the hang sits in native PJRT init
    where a python signal handler never runs. Call the returned function
    after ``jax.devices()`` succeeds to disarm."""
    discovered = threading.Event()

    def _watchdog():
        if not discovered.wait(timeout):
            print(f"{name}: TPU device discovery exceeded {timeout:.0f}s — "
                  "tunnel wedged; aborting", file=sys.stderr)
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()
    return discovered.set
