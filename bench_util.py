"""Shared bench-script utilities (stdlib only — imported before jax)."""

import datetime
import glob
import json
import os
import sys
import threading

_REPO = os.path.dirname(os.path.abspath(__file__))


def _bench_logs_dir():
    # DSTPU_BENCH_LOGS lets tests point at a hermetic tree.
    return os.environ.get("DSTPU_BENCH_LOGS",
                          os.path.join(_REPO, "bench_logs"))


def _headline_lines(path):
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and {"metric", "value", "unit"} <= set(rec):
                    yield rec
    except OSError:
        return


def latest_banked_result(metric: str = None):
    """Newest parseable headline JSON line under bench_logs/ whose metric
    matches ``metric`` (records for other metrics are REJECTED, never
    substituted — a wedged decode bench must not replay a training number).

    ``bench_logs/latest_headline.json`` (written by every successful
    ``bench.py`` run) wins outright when present and matching. Otherwise
    scans every ``*.json`` for matching headline lines; ties break by file
    mtime (newest first). Returns ``(record, source_path, mtime)`` or
    ``None``.
    """
    logs = _bench_logs_dir()
    canonical = os.path.join(logs, "latest_headline.json")
    for rec in _headline_lines(canonical):
        if metric is None or rec["metric"] == metric:
            return rec, canonical, os.path.getmtime(canonical)
    candidates = []
    for path in glob.glob(os.path.join(logs, "**", "*.json"), recursive=True):
        if path == canonical:
            continue
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        for rec in _headline_lines(path):
            if metric is None or rec["metric"] == metric:
                candidates.append((rec, path, mtime))
    if not candidates:
        return None
    return max(candidates, key=lambda c: c[2])


def bank_headline(record: dict, filename: str = "latest_headline.json"):
    """Persist a successful bench headline as a banked result.

    Best-effort (never fails the bench): writes the line to
    ``bench_logs/<filename>`` so a later wedged-tunnel run can replay it
    with stale provenance (``latest_headline.json`` is the canonical train
    headline; other benches bank under their own names and are found by
    metric match).
    """
    try:
        record = dict(record)
        record.setdefault("measured_at", datetime.datetime.now(
            datetime.timezone.utc).isoformat())
        path = os.path.join(_bench_logs_dir(), filename)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass


def emit_stale_banked(name: str, metric: str = None) -> bool:
    """Print the newest banked headline with stale-provenance fields.

    The round-end driver needs ONE parseable JSON line; when the axon tunnel
    is wedged (BENCH_r02..r04 were all rc=3 empties) the honest fallback is
    the most recent real-chip measurement, explicitly marked stale. Returns
    True if a line was printed.
    """
    found = latest_banked_result(metric)
    if not found:
        return False
    rec, path, mtime = found
    rec = dict(rec)
    rec["stale"] = True
    if "measured_at" not in rec:
        # mtime is the measurement time only for files written in place;
        # a fresh checkout resets it, so label the provenance honestly.
        rec["measured_at"] = datetime.datetime.fromtimestamp(
            mtime, datetime.timezone.utc).isoformat()
        rec["measured_at_source"] = "file_mtime"
    rec["source"] = os.path.relpath(path, _REPO)
    rec["stale_reason"] = f"{name}: TPU device discovery timed out (tunnel wedged)"
    print(json.dumps(rec))
    return True


# distinct from rc 3 (nothing banked) and rc 0 (fresh run): exit status alone
# must never conflate a stale replay with a real measurement
STALE_REPLAY_EXIT_CODE = 7


def guard_device_discovery(name: str, timeout: float = 180.0,
                           stale_metric: str = None):
    """Fail fast if TPU device discovery hangs (wedged axon tunnel, observed
    2026-07-30). A THREAD, not SIGALRM: the hang sits in native PJRT init
    where a python signal handler never runs. Call the returned function
    after ``jax.devices()`` succeeds to disarm.

    When ``stale_metric`` is set (the round-end driver path), a timeout
    emits the newest banked headline for that metric (marked
    ``stale: true``) and exits ``STALE_REPLAY_EXIT_CODE`` (7) so the driver
    records a parseable line while the exit status still says "replay, not
    fresh". Drivers that can only accept rc 0 opt in with
    ``DSTPU_STALE_REPLAY_RC0=1``. Exits 3 when nothing is banked or
    ``stale_metric`` is None.
    """
    discovered = threading.Event()

    def _watchdog():
        if not discovered.wait(timeout):
            print(f"{name}: TPU device discovery exceeded {timeout:.0f}s — "
                  "tunnel wedged", file=sys.stderr)
            if stale_metric is not None and emit_stale_banked(name, stale_metric):
                sys.stdout.flush()
                rc0 = os.environ.get("DSTPU_STALE_REPLAY_RC0", "") not in ("", "0")
                os._exit(0 if rc0 else STALE_REPLAY_EXIT_CODE)
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()
    return discovered.set
